#!/usr/bin/env bash
# Contract linter: the static-analysis pass over the repo's own invariants
# (docs/LINTING.md) — subject wiring, event-loop blocking calls, lock
# ordering, JAX recompile hygiene, C++ wire-contract parity, knob/doc
# drift. Device-free and fast (~2s); run it pre-merge alongside
# scripts/perf_gate.sh.
#
#   scripts/lint.sh                       # the whole pass (CI entrypoint)
#   scripts/lint.sh --rules cpp-parity    # one rule family
#   scripts/lint.sh --list                # rule catalog
#   scripts/lint.sh --tests               # + the pytest proof suite (-m lint)
#
# Exit codes: 0 clean, 1 findings (incl. stale allowlist entries), 2 usage.
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--tests" ]]; then
    python -m symbiont_tpu.lint
    # the proof suite: every rule fires on seeded fixtures, the allowlist
    # ratchet trips, the repo stays clean (tests/test_lint.py + the
    # pipeline-wiring shim)
    exec python -m pytest tests/ -m lint -q -p no:cacheprovider
fi
exec python -m symbiont_tpu.lint "$@"
