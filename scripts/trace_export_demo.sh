#!/usr/bin/env bash
# Export the most recent flight-recorder trace as a Perfetto-loadable
# Chrome Trace Format file (docs/OBSERVABILITY.md, obs/chrome_trace.py).
#
#   ./scripts/trace_export_demo.sh [host:port] [out.json]
#
# Picks the newest trace from GET /api/traces/recent (errored-first,
# slowest-first triage order), writes its export, and prints the one-line
# critical-path verdict alongside. Open the file at https://ui.perfetto.dev
# or chrome://tracing.
set -euo pipefail
API="${1:-localhost:8080}"
OUT="${2:-trace.json}"

TRACE_ID=$(curl -fsS "http://${API}/api/traces/recent" \
  | python3 -c 'import json,sys; t=json.load(sys.stdin)["traces"]; print(t[0]["trace_id"]) if t else sys.exit("no traces recorded yet — drive some traffic first")')

curl -fsS "http://${API}/api/traces/${TRACE_ID}/export?fmt=chrome" > "${OUT}"
echo "wrote ${OUT} (trace ${TRACE_ID}) — open it at https://ui.perfetto.dev"
curl -fsS "http://${API}/api/traces/${TRACE_ID}/critical_path" \
  | python3 -c 'import json,sys; print("verdict:", json.load(sys.stdin)["verdict"])'
