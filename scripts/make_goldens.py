"""Emit golden embedding vectors from a REAL pretrained checkpoint.

Run this WHERE the checkpoint (and torch/transformers) exist — typically the
same machine that ran scripts/fetch_model.py:

    python scripts/make_goldens.py models/minilm --out tests/goldens/minilm.npz

The .npz carries the canonical texts, transformers' reference mean-pooled
embeddings, and the model fingerprint. Check it into the repo: then ANY
environment holding the checkpoint — including slim TPU hosts with no
torch — can validate the full JAX load+embed path against it:

    SYMBIONT_MODEL_DIR=models/minilm \
    SYMBIONT_GOLDEN_FILE=tests/goldens/minilm.npz \
    python -m pytest tests/test_golden_vectors.py -q

This closes the loop VERDICT r3 item 8 asks for: the reference embeds
meaningfully from first boot (embedding_generator.rs:25-58); here the gated
tier proves the same the moment a snapshot exists, without re-downloading
torch's half of the comparison.
"""

from __future__ import annotations

import argparse
import hashlib
import json
from pathlib import Path

import numpy as np

# Canonical corpus: mixed length, a paraphrase pair (0, 1) and an unrelated
# sentence (2) for the semantic sanity check, some long tails for bucketing.
GOLDEN_TEXTS = [
    "A cat sits on the mat.",
    "A kitten rests on a rug.",
    "The stock market fell sharply today.",
    "High bandwidth memory feeds the systolic matrix unit of the chip.",
    "Sentence embeddings are pooled from the final hidden states of the "
    "encoder and ranked by cosine similarity against the corpus.",
    "short",
    "The quick brown fox jumps over the lazy dog " * 8,
]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("model_dir")
    ap.add_argument("--out", default=None,
                    help="output npz (default tests/goldens/<dirname>.npz)")
    args = ap.parse_args()

    import torch
    import transformers

    d = Path(args.model_dir)
    model = transformers.AutoModel.from_pretrained(d).eval()
    tok = transformers.AutoTokenizer.from_pretrained(d)
    # truncate to the model's position budget (mirrors the engine's own
    # min(bucket, max_position_embeddings) clamp; HF LongestFirst and the
    # engine's keep-prefix+SEP truncation produce identical single-sequence
    # results — tests/test_real_assets.py asserts the parity)
    max_len = int(getattr(model.config, "max_position_embeddings", 512))
    if tok.model_max_length and tok.model_max_length < 10**6:
        max_len = min(max_len, int(tok.model_max_length))
    enc = tok(GOLDEN_TEXTS, padding=True, truncation=True, max_length=max_len,
              return_tensors="pt")
    with torch.no_grad():
        h = model(**{k: v for k, v in enc.items()
                     if k in ("input_ids", "attention_mask")}).last_hidden_state
    m = enc["attention_mask"].unsqueeze(-1).float()
    ref = ((h * m).sum(1) / m.sum(1)).numpy().astype(np.float32)

    cfg_text = (d / "config.json").read_text()
    out = Path(args.out) if args.out else (
        Path(__file__).resolve().parent.parent / "tests" / "goldens" /
        f"{d.name}.npz")
    out.parent.mkdir(parents=True, exist_ok=True)
    np.savez(
        out,
        texts=np.array(GOLDEN_TEXTS),
        embeddings=ref,
        config_sha=hashlib.sha256(cfg_text.encode()).hexdigest(),
        model_type=json.loads(cfg_text).get("model_type", "?"),
    )
    print(f"wrote {out}: {ref.shape[0]} texts x {ref.shape[1]} dims "
          f"(config sha {hashlib.sha256(cfg_text.encode()).hexdigest()[:12]})")


if __name__ == "__main__":
    main()
