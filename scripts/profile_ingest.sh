#!/usr/bin/env bash
# Localize a host-overlap regression in ONE command (ROADMAP item 3, the
# overlap-everything ingest rework — docs/PERF.md "Overlap-everything
# ingest" section): where does e2e ingest time actually go?
#
#   scripts/profile_ingest.sh                  # run the bench e2e tier
#       (full stack: native broker + C++ workers + engine plane), then
#       print the archived "where the time goes" ingest stage shares, the
#       e2e÷bulk ratio vs the ≥0.6 target, and the overlap/coalesce stats.
#
#   scripts/profile_ingest.sh localhost:8080   # against a RUNNING stack:
#       pick the slowest recent ingest trace from GET /api/traces and print
#       its critical path — per-hop self-times, the dominant-hop verdict,
#       and gap_ms (untraced time: bus queueing / scheduling / span-less
#       native hops). A growing gap_ms is host overlap regressing.
#
#   scripts/profile_ingest.sh --decode [host:port]   # against a RUNNING
#       stack (default localhost:8080): print the newest engine-timeline
#       summary (GET /api/engine/timeline, obs/engine_timeline.py) the way
#       the ingest mode prints hop self-times — decode batch occupancy,
#       stranded KV rows, prefix share, TTFT/TPOT, embed packing
#       opportunity, and the dominant-stall verdict.
#
#   scripts/profile_ingest.sh --memory [host:port]   # against a RUNNING
#       stack (default localhost:8080): print the HBM attribution plane
#       (GET /api/memory + /api/memory/census, obs/hbm.py) — per-subsystem
#       byte ledger, per-device bytes-in-use/limit/headroom, the
#       unattributed residual, the last OOM verdict, and the top
#       live-array census groups.
set -euo pipefail
cd "$(dirname "$0")/.."

if [ "${1:-}" = "--decode" ]; then
  python3 - "${2:-localhost:8080}" <<'EOF'
import json
import sys
import urllib.request

api = sys.argv[1]
with urllib.request.urlopen(f"http://{api}/api/engine/timeline",
                            timeout=10) as r:
    s = json.load(r)["summary"]
if not s["decode_steps"] and not s["embed_flushes"]:
    sys.exit("no engine timeline recorded yet — drive some embed/decode "
             "traffic first")
print(f"engine timeline window: {s['decode_steps']} decode steps, "
      f"{s['decode_admits']} admits, {s['decode_finishes']} finishes, "
      f"{s['decode_cancels']} cancels, {s['embed_flushes']} embed flushes")
rows = [
    ("decode batch occupancy", f"{s['decode_occupancy_pct']}%"),
    ("stranded KV rows", f"{s['decode_kv_stranded_pct']}% of allocated"),
    ("prompt prefix share", f"{s['decode_prefix_share_pct']}%"),
    ("TTFT p50 / p99", f"{s['decode_ttft_ms_p50']} / "
                       f"{s['decode_ttft_ms_p99']} ms"),
    ("TPOT p50", f"{s['decode_tpot_ms_p50']} ms/token"),
    ("prefill vs decode wall", f"{s['decode_prefill_ms_total']} / "
                               f"{s['decode_step_ms_total']} ms"),
    ("embed packing opportunity", f"{s['packing_opportunity_pct']}%"),
]
for name, val in rows:
    print("  " + name.ljust(28) + val)
# paged-KV + radix rows appear only when the engine runs kv_layout=paged
# (summary fields) and the kv.* gauges are registered — guard every key
if s.get("decode_radix_hit_pct") is not None:
    paged = [
        ("radix prompt-token hits", f"{s['decode_radix_hit_pct']}%"),
        ("TTFT radix-hit / cold", f"{s.get('decode_ttft_hit_ms_p50', '-')} "
                                  f"/ {s.get('decode_ttft_cold_ms_p50', '-')}"
                                  " ms"),
        ("KV pages live", f"{s.get('decode_pages_live_pct', '-')}% of pool"),
    ]
    try:
        with urllib.request.urlopen(f"http://{api}/api/metrics",
                                    timeout=10) as r:
            g = json.load(r).get("gauges", {})
        def kv(name):
            for k, v in g.items():
                if k == name or k.startswith(name + "{"):
                    return v
            return None
    except Exception:
        def kv(name):
            return None
    free, live, frag = (kv("kv.pages_free"), kv("kv.pages_live"),
                        kv("kv.page_fragmentation_pct"))
    if free is not None or live is not None:
        paged.append(("page pool free / live",
                      f"{'-' if free is None else int(free)} / "
                      f"{'-' if live is None else int(live)} pages"))
    if frag is not None:
        paged.append(("page fragmentation", f"{frag}%"))
    for name, val in paged:
        print("  " + name.ljust(28) + val)
# compute-plane dispatch rows (obs/xprof.py host-gap attribution) appear
# only once decode steps carry dispatch counts — guard like the paged rows
if s.get("decode_host_gap_pct") is not None:
    print("  " + "dispatches per token".ljust(28)
          + f"{s['decode_dispatches_per_token']}")
    print("  " + "host gap (chunk wall)".ljust(28)
          + f"{s['decode_host_gap_pct']}% host-side between dispatches")
# speculative-decode rows (engine/lm.py draft plane) appear only when the
# window recorded spec rounds — spec-off deployments print unchanged
if s.get("decode_spec_accept_pct") is not None:
    print("  " + "spec accept rate".ljust(28)
          + f"{s['decode_spec_accept_pct']}% over "
            f"{s.get('decode_spec_rounds', 0)} rounds")
    print("  " + "spec draft / verify wall".ljust(28)
          + f"{s.get('decode_spec_draft_ms_total', 0)} / "
            f"{s.get('decode_spec_verify_ms_total', 0)} ms")
print("dominant stall:", s["dominant_stall"])
print(f"(Perfetto view: curl http://{api}"
      "'/api/engine/timeline?fmt=chrome' > tl.json, open in "
      "ui.perfetto.dev)")
EOF
  exit 0
fi

if [ "${1:-}" = "--memory" ]; then
  python3 - "${2:-localhost:8080}" <<'EOF'
import json
import sys
import urllib.request

api = sys.argv[1]
with urllib.request.urlopen(f"http://{api}/api/memory", timeout=10) as r:
    mem = json.load(r)
local = mem.get("local") or {}
rows = local.get("subsystems") or []


def gib(n):
    return f"{n / (1 << 30):8.3f} GiB" if n is not None else "       -    "


print(f"hbm attribution (basis: {local.get('basis')})")
if not rows:
    print("  no subsystem claims yet — is an engine plane up on this role?")
for row in rows:
    mark = "  (overlay: inside another claim)" if row["overlay"] else ""
    print("  " + row["subsystem"].ljust(24) + gib(row["bytes"]) + mark)
print("  " + "-" * 44)
print("  " + "attributed".ljust(24) + gib(local.get("attributed_bytes")))
print("  " + "unattributed".ljust(24) + gib(local.get("unattributed_bytes"))
      + f"  ({local.get('unattributed_pct')}% of "
        f"{gib(local.get('bytes_in_use')).strip()} in use)")
for d in local.get("devices") or []:
    limit, use = d.get("bytes_limit"), d["bytes_in_use"]
    head = (limit - use) if limit else None
    print(f"  device {d['device']} ({d['platform']}): "
          f"{gib(use).strip()} in use / {gib(limit).strip()} limit"
          + (f", {gib(head).strip()} headroom" if head is not None else ""))
oom = mem.get("last_oom")
if oom:
    print(f"LAST OOM: site={oom['site']} postmortem={oom.get('postmortem')}")
    print(f"  {oom.get('error', '')[:120]}")
for role, entry in (mem.get("roles") or {}).items():
    subs = entry.get("subsystems") or {}
    if subs:
        total = sum(v for v in subs.values())
        print(f"  role {role}: {len(subs)} subsystem claims, "
              f"{gib(total).strip()} attributed")
with urllib.request.urlopen(f"http://{api}/api/memory/census?top=8",
                            timeout=10) as r:
    cen = json.load(r)["census"]
if cen.get("available"):
    print(f"live-array census: {cen['arrays']} arrays, "
          f"{gib(cen['bytes_total']).strip()} total")
    for g in cen["groups"][:8]:
        shape = "x".join(str(d) for d in g["shape"]) or "scalar"
        print(f"  {g['dtype']:<10} {shape:<22} x{g['count']:<5} "
              + gib(g["bytes"]).strip())
else:
    print("live-array census unavailable:", cen.get("detail"))
EOF
  exit 0
fi

if [ $# -ge 1 ]; then
  python3 - "$1" <<'EOF'
import json
import sys
import urllib.request

api = sys.argv[1]
with urllib.request.urlopen(f"http://{api}/api/traces/recent",
                            timeout=10) as r:
    traces = json.load(r)["traces"]
ingest_roots = ("api.submit_url", "perception.handle", "preprocessing.handle",
                "vector_memory.handle", "engine.handle")
picks = [t for t in traces if t.get("root") in ingest_roots] or traces
if not picks:
    sys.exit("no traces recorded yet — drive some ingest first")
tid = picks[0]["trace_id"]
with urllib.request.urlopen(f"http://{api}/api/traces/{tid}/critical_path",
                            timeout=10) as r:
    cp = json.load(r)
print(f"trace {tid} (root {picks[0].get('root')}, e2e {cp.get('e2e_ms')} ms)")
for hop in cp.get("chain", []):
    print("  " + hop["name"].ljust(40)
          + f" self {hop['self_ms']:>9} ms  ({hop['share_of_e2e_pct']}%)")
print("  " + "<untraced gap>".ljust(40)
      + f" self {cp['gap_ms']:>9} ms  ({cp.get('gap_pct')}%)")
print("verdict:", cp.get("verdict"))
EOF
  exit 0
fi

# no host given: run the bench (e2e tier included) and read its archived
# attribution + overlap fields off the one JSON line it prints on stdout
LINE_FILE="$(mktemp)"
trap 'rm -f "${LINE_FILE}"' EXIT
python bench.py --no-chaos | tee "${LINE_FILE}"
python3 - "${LINE_FILE}" <<'EOF'
import json, sys
line = [l for l in open(sys.argv[1]) if l.strip().startswith("{")][-1]
r = json.loads(line)
stages = sorted(((k, v) for k, v in r.items()
                 if k.startswith("e2e_stage_ingest_") and k.endswith("_pct")),
                key=lambda kv: -kv[1])
print()
print("== where the ingest time goes (critical-path self-time shares) ==")
if not stages:
    print("no e2e_stage_ingest_* fields archived — did the e2e tier run?")
for k, v in stages:
    hop = k[len("e2e_stage_ingest_"):-len("_pct")]
    marker = "  <- dominant" if (k, v) == stages[0] else ""
    if hop == "gap":
        marker = "  (untraced: bus queueing / span-less native hops)"
    print(f"  {hop:<32} {v:>6.1f}%{marker}")
ratio = r.get("e2e_ingest_vs_bulk_x")
if ratio is not None:
    verdict = "OK" if ratio >= 0.6 else "REGRESSION (target >= 0.6)"
    print(f"e2e ingest / bulk ingest: {ratio}x  [{verdict}]")
ov = r.get("e2e_batcher_overlap_ratio")
if ov is not None:
    print(f"embed flush window overlap ratio: {ov}")
rows = r.get("e2e_coalesce_rows_per_flush")
if rows is not None:
    print(f"coalesced upsert: {rows} rows/flush over "
          f"{r.get('e2e_coalesce_flushes')} flushes")
EOF
