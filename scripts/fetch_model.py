"""Fetch a pretrained checkpoint snapshot for the engine (needs egress).

The reference downloads its model from the HF hub on every boot
(reference: services/preprocessing_service/src/embedding_generator.rs:25-58);
this framework is offline-first — the engine only ever reads a LOCAL model
dir (config.engine.model_dir). This script is the documented bridge: run it
once where egress exists, ship the directory, point the engine at it.

    python scripts/fetch_model.py sentence-transformers/all-MiniLM-L6-v2 \
        --out models/minilm
    SYMBIONT_ENGINE_MODEL_DIR=models/minilm python -m symbiont_tpu.runner engine

Then (optional) pre-convert so engine restarts skip conversion entirely:

    python -m symbiont_tpu.models.convert models/minilm --out models/minilm-ckpt

The gated test tier validates a fetched snapshot end-to-end:

    SYMBIONT_MODEL_DIR=models/minilm python -m pytest tests/test_real_assets.py -q

Then emit golden vectors (scripts/make_goldens.py) and check them in, so
torch-free hosts can re-validate the JAX path against transformers outputs
forever after (tests/test_golden_vectors.py).

BASELINE.md model set: sentence-transformers/all-MiniLM-L6-v2 (config #1),
BAAI/bge-base-en-v1.5 (#2), intfloat/e5-large-v2 (#3),
cross-encoder/ms-marco-MiniLM-L-6-v2 (#4, use --pooler when converting),
sentence-transformers/paraphrase-multilingual-mpnet-base-v2 (the reference's
default, main.rs:305).
"""

from __future__ import annotations

import argparse
from pathlib import Path

NEEDED = ["config.json", "tokenizer.json", "tokenizer_config.json",
          "special_tokens_map.json", "vocab.txt", "sentencepiece.bpe.model",
          "*.safetensors", "*.safetensors.index.json"]
# load_state_dict handles a SINGLE-file torch checkpoint too (convert.py:58-63)
# — fetched only as a fallback so safetensors-shipping repos don't pull both
# formats. Sharded .bin (pytorch_model-*-of-*.bin) is NOT loadable; repos that
# ship only that format need a transformers conversion first.
BIN_FALLBACK = ["pytorch_model.bin"]


def _weight_files(out: Path) -> list:
    """Files load_state_dict can actually boot from."""
    return [p.name for p in out.iterdir() if p.is_file() and
            (p.name.endswith(".safetensors")
             or p.name.endswith(".safetensors.index.json")
             or p.name == "pytorch_model.bin")]


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model_id", help="hub id, e.g. sentence-transformers/all-MiniLM-L6-v2")
    ap.add_argument("--out", required=True, help="local directory to populate")
    ap.add_argument("--revision", default="main")
    args = ap.parse_args(argv)

    from huggingface_hub import snapshot_download

    path = snapshot_download(
        args.model_id, revision=args.revision, allow_patterns=NEEDED,
        local_dir=args.out)
    out = Path(path)
    if not _weight_files(out):
        print("no safetensors in snapshot — falling back to torch .bin weights")
        snapshot_download(
            args.model_id, revision=args.revision,
            allow_patterns=NEEDED + BIN_FALLBACK, local_dir=args.out)
    # top-level regular files only: the hub's .cache bookkeeping dir lives
    # inside local_dir and is not part of the snapshot
    have = sorted(p.name for p in out.iterdir() if p.is_file())
    print(f"fetched {args.model_id}@{args.revision} -> {out}")
    print(f"files: {have}")
    if not _weight_files(out):
        raise SystemExit("snapshot has no safetensors or single-file "
                         "pytorch_model.bin — convert with transformers first "
                         "(sharded .bin checkpoints are not loadable here)")


if __name__ == "__main__":
    main()
