"""Fetch a pretrained checkpoint snapshot for the engine (needs egress).

The reference downloads its model from the HF hub on every boot
(reference: services/preprocessing_service/src/embedding_generator.rs:25-58);
this framework is offline-first — the engine only ever reads a LOCAL model
dir (config.engine.model_dir). This script is the documented bridge: run it
once where egress exists, ship the directory, point the engine at it.

    python scripts/fetch_model.py sentence-transformers/all-MiniLM-L6-v2 \
        --out models/minilm
    SYMBIONT_ENGINE_MODEL_DIR=models/minilm python -m symbiont_tpu.runner engine

Then (optional) pre-convert so engine restarts skip conversion entirely:

    python -m symbiont_tpu.models.convert models/minilm --out models/minilm-ckpt

The gated test tier validates a fetched snapshot end-to-end:

    SYMBIONT_MODEL_DIR=models/minilm python -m pytest tests/test_real_assets.py -q

BASELINE.md model set: sentence-transformers/all-MiniLM-L6-v2 (config #1),
BAAI/bge-base-en-v1.5 (#2), intfloat/e5-large-v2 (#3),
cross-encoder/ms-marco-MiniLM-L-6-v2 (#4, use --pooler when converting),
sentence-transformers/paraphrase-multilingual-mpnet-base-v2 (the reference's
default, main.rs:305).
"""

from __future__ import annotations

import argparse
from pathlib import Path

NEEDED = ["config.json", "tokenizer.json", "tokenizer_config.json",
          "special_tokens_map.json", "vocab.txt", "sentencepiece.bpe.model",
          "*.safetensors", "*.safetensors.index.json"]


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model_id", help="hub id, e.g. sentence-transformers/all-MiniLM-L6-v2")
    ap.add_argument("--out", required=True, help="local directory to populate")
    ap.add_argument("--revision", default="main")
    args = ap.parse_args(argv)

    from huggingface_hub import snapshot_download

    path = snapshot_download(
        args.model_id, revision=args.revision, allow_patterns=NEEDED,
        local_dir=args.out)
    out = Path(path)
    have = sorted(p.name for p in out.iterdir())
    print(f"fetched {args.model_id}@{args.revision} -> {out}")
    print(f"files: {have}")
    if not any(n.endswith(".safetensors") or n.endswith(".index.json") for n in have):
        raise SystemExit("no safetensors in snapshot — this repo may only ship "
                         ".bin weights; re-run without allow_patterns or convert "
                         "with transformers first")


if __name__ == "__main__":
    main()
