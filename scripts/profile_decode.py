"""Decode batch-scaling profiler (VERDICT r4 next-3).

Why does TinyLlama's per-step decode time triple from batch 8 to 128 when
weight reads — which every row shares — dominate the HBM traffic? This
script isolates the per-row suspects on the real chip by timing the SAME
chunked-decode loop with components ablated:

  full      : temperature=0.8, top_k=40  (lax.top_k bucket + categorical)
  no_topk   : temperature=0.8, top_k=0   (categorical only)
  greedy    : _sample monkeypatched to pure argmax (no RNG, no top_k)

and across cache sizes (NEW=128 vs 896) to expose the padded-cache-read
term (attention always reads the full [B, P+NEW] cache, valid or not).

Prints one JSON line per (geometry, batch, variant) with ms/step and the
HBM roofline context. Safe to run anywhere; meaningful on the TPU.

Usage: python scripts/profile_decode.py [--quick]
"""

from __future__ import annotations

import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

GEOMETRIES = {
    "tinyllama_1b": dict(vocab_size=32000, hidden_size=2048, num_layers=22,
                         num_heads=32, num_kv_heads=4, intermediate_size=5632,
                         max_position_embeddings=2048, arch="llama"),
    "gpt2_124m": dict(vocab_size=50257, hidden_size=768, num_layers=12,
                      num_heads=12, intermediate_size=3072,
                      max_position_embeddings=1024, arch="gpt2"),
}


def param_bytes(params) -> int:
    import jax

    return sum(a.size * a.dtype.itemsize for a in jax.tree.leaves(params))


def time_decode(gpt_mod, params, cfg, B, P, NEW, chunk, temperature, top_k,
                steps) -> float:
    """ms per decode step over `steps` chunked steps (fresh state, warmed)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, P)), jnp.int32)
    mask = jnp.ones((B, P), jnp.int32)
    key = jax.random.key(0)

    def run(n_steps):
        cache, logits, kv_valid, plen = gpt_mod.prefill(params, ids, mask,
                                                        cfg, NEW)
        pos, done = plen, jnp.zeros((B,), bool)
        n = 0
        toks = None
        while n < n_steps:
            keys = jax.random.split(jax.random.fold_in(key, n), chunk)
            (cache, logits, pos, done, toks, _) = gpt_mod.decode_chunk(
                params, cache, logits, pos, done, kv_valid, keys, cfg,
                temperature=temperature, top_k=top_k, eos_id=-1)
            n += chunk
        # materialize: the only honest completion barrier on a
        # network-attached runtime (see bench.py run())
        np.asarray(toks)

    run(chunk)          # compile prefill + chunk executable
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        run(steps)
        best = min(best, time.time() - t0)
    return best / steps * 1000.0


def main() -> None:
    import jax

    from symbiont_tpu.models import gpt as gpt_mod

    quick = "--quick" in sys.argv
    dev = jax.devices()[0]
    print(f"# device: {dev.device_kind} ({dev.platform})", file=sys.stderr)

    for name, kw in GEOMETRIES.items():
        if quick and name != "tinyllama_1b":
            continue
        cfg = gpt_mod.GPTConfig(dtype="bfloat16", **kw)
        params = jax.device_put(gpt_mod.init_params(jax.random.key(0), cfg))
        pbytes = param_bytes(params)
        P, chunk = 64, 16
        steps = 32 if quick else 64

        orig_sample = gpt_mod._sample

        def argmax_sample(logits, key, temperature, top_k, top_k_bucket):
            import jax.numpy as jnp

            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        for NEW in (128, 896):
            for B in ((8, 128) if quick else (8, 32, 128)):
                row = {"geometry": name, "batch": B, "prompt": P, "new": NEW,
                       "param_bytes": pbytes}
                # KV bytes READ per step: full padded cache, both k and v
                T = P + NEW
                nkv = cfg.kv_heads
                row["kv_read_bytes_per_step"] = (
                    2 * cfg.num_layers * B * T * nkv * cfg.head_dim * 2)
                for variant, (t, k) in {
                    "full": (0.8, 40), "no_topk": (0.8, 0),
                }.items():
                    ms = time_decode(gpt_mod, params, cfg, B, P, NEW, chunk,
                                     t, k, steps)
                    row[f"ms_per_step_{variant}"] = round(ms, 3)
                # greedy-argmax: swap _sample out and drop the jit cache so
                # the ablated body actually recompiles
                gpt_mod._sample = argmax_sample
                gpt_mod._decode_chunk_jit.clear_cache()
                try:
                    ms = time_decode(gpt_mod, params, cfg, B, P, NEW, chunk,
                                     0.8, 40, steps)
                    row["ms_per_step_argmax"] = round(ms, 3)
                finally:
                    gpt_mod._sample = orig_sample
                    gpt_mod._decode_chunk_jit.clear_cache()
                row["tok_per_s_full"] = round(
                    B / row["ms_per_step_full"] * 1000, 1)
                print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
