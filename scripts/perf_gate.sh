#!/usr/bin/env bash
# Standing pre-merge perf gate (ROADMAP item 1's unlanded half, docs/PERF.md
# "Methodology notes"): one command that exits nonzero on any PRIMARY metric
# regression beyond the noise bars, wrapping the existing
# `python bench.py --gate` machinery (symbiont_tpu/bench/archive.py —
# per-metric thresholds = max(family floor, 1.5x the baseline's archived
# in-run spread); tunnel-bound fields are never gated).
#
# Usage:
#   scripts/perf_gate.sh                 # run the host-only micro-tiers
#                                        # (--only obs,serialization: ~1 min,
#                                        # no device, no engine compile) and
#                                        # gate them against the quick
#                                        # baseline
#   scripts/perf_gate.sh CANDIDATE.json  # gate an existing archive line
#                                        # (e.g. a fresh full-run
#                                        # BENCH_LATEST) without re-running
#
# Baseline resolution:
#   PERF_GATE_BASELINE env var when set; else, for the quick-run mode,
#   BENCH_GATE_BASELINE.json (the committed quick-tier baseline — the full
#   BENCH_LATEST.json predates the quick tiers' primaries, so the two
#   declare disjoint metric sets and bench.py --gate would correctly refuse
#   the vacuous comparison); else BENCH_LATEST.json. Candidate mode defaults
#   to BENCH_LATEST.json (full archives compare like for like).
#
# Exit code: 0 = no regression; nonzero = regression, lost primary, schema
# problem, or a red bench run. tests/test_perf_gate.py (-m gate) pins both
# directions so this script cannot rot.
set -u
cd "$(dirname "$0")/.."

CANDIDATE="${1:-}"
if [ -n "$CANDIDATE" ]; then
  BASELINE="${PERF_GATE_BASELINE:-BENCH_LATEST.json}"
else
  if [ -n "${PERF_GATE_BASELINE:-}" ]; then
    BASELINE="$PERF_GATE_BASELINE"
  elif [ -f BENCH_GATE_BASELINE.json ]; then
    BASELINE="BENCH_GATE_BASELINE.json"
  else
    BASELINE="BENCH_LATEST.json"
  fi
  CANDIDATE="$(mktemp /tmp/perf_gate_candidate.XXXXXX.json)"
  trap 'rm -f "$CANDIDATE"' EXIT
  # --only never persists BENCH_LATEST.json (a partial line must not
  # become the doc's source) — exactly right for a gate probe. The
  # embed-policy tier is deliberately NOT in the default set: it needs a
  # real device to be meaningful and takes minutes of CPU without one.
  # The obs tier's primaries cover the whole telemetry hot path: span
  # exits, critical-path compute, fleet merge, AND the engine-timeline
  # record cost every decode chunk boundary pays
  # (obs_timeline_record_per_s).
  TIERS="${PERF_GATE_TIERS:-obs,serialization}"
  echo "perf_gate: running host-only micro-tiers (bench.py --only $TIERS)" >&2
  if ! python bench.py --only "$TIERS" ${PERF_GATE_ARGS:-} > "$CANDIDATE"; then
    echo "perf_gate: bench run FAILED (tier failure or missing primary —" \
         "see the line above); refusing to gate a red run" >&2
    exit 1
  fi
fi

echo "perf_gate: gating $CANDIDATE against $BASELINE" >&2
python bench.py --gate "$CANDIDATE" "$BASELINE"
rc=$?
if [ "$rc" -ne 0 ]; then
  # A red gate against a baseline archived on a DIFFERENT machine is very
  # often the environment, not the code: the quick tiers are pure host CPU
  # timing, and BENCH_GATE_BASELINE numbers from one CPU model do not bound
  # another. Every emitted line carries archive.host_fingerprint(); compare
  # the baseline's against this host and shout when they disagree. The gate
  # verdict (rc) is NOT changed — a mismatch explains, it never excuses.
  python - "$BASELINE" >&2 <<'PY' || true
import sys
from symbiont_tpu.bench.archive import host_fingerprint, load_archive

base = load_archive(sys.argv[1])
cur = host_fingerprint()
mismatch = [(k, base[k], cur.get(k)) for k in ("host_cpu_model",
                                               "host_cpu_cores")
            if k in base and base[k] != cur.get(k)]
if mismatch:
    bar = "!" * 72
    print(bar)
    print("perf_gate: ENVIRONMENT MISMATCH — the baseline was archived on "
          "a different host.")
    for k, b, c in mismatch:
        print(f"perf_gate:   {k}: baseline={b!r}  this host={c!r}")
    print("perf_gate: host-only micro-tier numbers are CPU-bound; re-baseline"
          " on THIS host")
    print("perf_gate: (python bench.py --only obs,serialization > "
          "BENCH_GATE_BASELINE.json) before trusting this verdict.")
    print(bar)
elif "host_cpu_model" not in base:
    print("perf_gate: note: baseline archives no host fingerprint "
          "(pre-fingerprint line) — cannot rule out an environment "
          "mismatch behind this failure.")
PY
fi
exit "$rc"
