#!/usr/bin/env bash
# Chaos suite: seeded fault-injection scenarios asserting zero-loss ingest
# under each fault class (docs/RESILIENCE.md). Deterministic (seeded
# FaultPlans) and device-free — runs anywhere the fast test tier runs.
#
#   scripts/chaos.sh            # the whole suite
#   scripts/chaos.sh -k poison  # one scenario
#
# The same suite runs as the bench subsystem's `chaos` tier
# (symbiont_tpu/bench/chaos.py), where its pass rate is archived and
# regression-gated like a perf metric; this script is the fast local loop.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# scoped to the chaos module (not the whole tree) so an unrelated module's
# env-dependent collection error can't block the fault suite
exec python -m pytest tests/test_chaos.py -m chaos -q "$@"
