#!/usr/bin/env bash
# Process-failure plane, one command (docs/RESILIENCE.md "process
# supervision"): broker + supervised worker PROCESSES + the multi-tenant
# load simulator, with a seeded kill-chaos plan SIGKILLing one worker,
# SIGSTOPping another, and SIGKILLing the broker itself mid-run — hard
# gates: exact zero-loss ingest, Jain fairness >= 0.8, zero final queue
# depth, and the kill->serving-again recovery time archived as
# `load_proc_recovery_s`. Since the fleet telemetry plane (obs/fleet.py)
# the tier also hard-gates the observability story of that deployment:
# every supervised role (procsup's own gauges and the broker probe
# included) in ONE role-labeled /metrics exposition, and a client-carried
# trace across >= 3 OS processes returned as a single stitched tree
# (`load_mp_fleet_roles` / `load_mp_trace_stitched`; roll-up archived as
# `fleet_snapshot`).
#
#   scripts/multiproc.sh                 # chaos scenarios + the bench tier
#   scripts/multiproc.sh --tests-only    # just the pytest chaos scenarios
#   scripts/multiproc.sh --seed 7        # replay a specific kill plan
#   scripts/multiproc.sh --ramp          # the traffic-ramp AUTOSCALER
#                                        # phase standalone (load_ramp
#                                        # tier: 4x open-loop ramp, kill
#                                        # plan firing, scale-out + drained
#                                        # scale-in hard gates — docs/
#                                        # RESILIENCE.md "Elastic
#                                        # autoscaling")
#   scripts/multiproc.sh --gen-chaos     # the DURABLE-GENERATION phase
#                                        # (load_multiproc_gen tier: two
#                                        # journalled LM workers, SIGKILL
#                                        # mid token stream, exactly-once
#                                        # SSE gates — docs/RESILIENCE.md
#                                        # "Durable generation sessions")
#
# Device-free: workers run tiny real engines on the JAX CPU backend; the
# broker is the pure-Python symbus twin (bus/pybroker.py) where the native
# build is unavailable — same wire protocol, same .symlog durability.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

seed=1
tests_only=0
ramp=0
gen_chaos=0
prev=""
for arg in "$@"; do
  case "$arg" in
    --tests-only) tests_only=1 ;;
    --ramp) ramp=1 ;;
    --gen-chaos) gen_chaos=1 ;;
    --seed) prev="seed" ;;
    *) if [[ "$prev" == "seed" ]]; then seed="$arg"; prev=""; fi ;;
  esac
done

if [[ "$gen_chaos" -eq 1 ]]; then
  echo "== durable-generation chaos scenarios (journal, resume, rescue) ==" >&2
  python -m pytest tests/test_gen_durability.py -q
  echo "== load_multiproc_gen bench tier (mid-stream SIGKILL, seed ${seed}) ==" >&2
  exec python bench.py --only load_multiproc_gen --gen-chaos \
    --load-seed "${seed}" --chaos-seed "${seed}"
fi

if [[ "$ramp" -eq 1 ]]; then
  echo "== drain-protocol chaos scenarios (scale-out/in, mid-drain kill) ==" >&2
  python -m pytest tests/test_autoscale.py -m chaos -q
  echo "== load_ramp bench tier (4x traffic ramp + autoscaler, seed ${seed}) ==" >&2
  exec python bench.py --only load_ramp --ramp \
    --load-seed "${seed}" --chaos-seed "${seed}"
fi

echo "== process-failure chaos scenarios (pybroker + supervisor) ==" >&2
python -m pytest tests/test_procsup.py -m chaos -q

if [[ "$tests_only" -eq 1 ]]; then
  exit 0
fi

echo "== load_multiproc bench tier (kill-chaos, seed ${seed}) ==" >&2
exec python bench.py --only load_multiproc --multiproc \
  --load-seed "${seed}" --chaos-seed "${seed}"
