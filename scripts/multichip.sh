#!/usr/bin/env bash
# Multi-chip serving plane: parity tests + the `multichip` bench tier on 8
# SIMULATED host devices (docs/SCALING.md). Device-free — runs anywhere the
# fast test tier runs; XLA splits the host CPU into 8 virtual devices, so
# the REAL sharded code paths (DP embed over 'data', per-shard top-k +
# global merge, TP decode collectives) execute exactly as on a pod.
#
#   scripts/multichip.sh                # parity suite + multichip tier
#   scripts/multichip.sh --tests-only   # just the tier-1 parity suite
#   scripts/multichip.sh --mesh dp4xtp2 # tier at a specific mesh shape
#
# NOTE on the numbers: simulated devices share the same cores, so the
# archived mc_scale_efficiency_* values are bounded by ~1/n here and only
# prove the plumbing; the >= 0.8 bar is judged on real chips (the parity
# gates — identical search results, token-identical decode — are hard
# everywhere).
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

mesh_args=()
tests_only=0
for arg in "$@"; do
  case "$arg" in
    --tests-only) tests_only=1 ;;
    --mesh) mesh_args+=(--mesh) ;;
    *) [[ ${#mesh_args[@]} -eq 1 ]] && mesh_args+=("$arg") ;;
  esac
done

echo "== multichip parity suite (8 simulated devices) ==" >&2
python -m pytest tests/test_multichip_serving.py -q

if [[ "$tests_only" -eq 1 ]]; then
  exit 0
fi

echo "== multichip bench tier ==" >&2
exec python bench.py --only multichip "${mesh_args[@]}"
