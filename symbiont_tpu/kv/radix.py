"""Refcounted radix prefix cache over committed prompt pages.

A token trie at PAGE granularity: each node is one prompt block (the
``kv_page_tokens`` token ids covering cache slots ``[b·page, (b+1)·page)``
of a right-aligned prompt row) and owns the pool page holding that block's
K/V. An admit walks the trie with its own prompt blocks; every matched
node's page is wired straight into the new row's page table (pool
refcount++) instead of being re-materialized — the prompt's shared prefix
is prefilled ONCE per process, not once per session. The first divergent
block ends the walk: the row gets a fresh private page there (the
copy-on-write fork — the fork block's pre-divergence slots are
re-materialized into the private page by the row's own prefill scatter,
never written into the shared page).

Trie roots are keyed by ``(prompt_bucket, pad)``: right-alignment makes a
slot's K/V depend on its logical position (= slot − pad), so only rows
with equal prompt length inside the same bucket can share pages. That is
the honest limitation of page-sharing under right-aligned static shapes —
and the common RAG-template workload (fixed template + fixed-width query
slot) sits squarely inside it (docs/KV.md).

A FULL-prompt terminal additionally stores the last-token logits (host
numpy, one [vocab] row), so an admit whose entire prompt is committed
skips its prefill outright: pages are wired, logits restored, and TTFT
collapses to ~one decode chunk (the tentpole's radix-hit gate).

Eviction: committed pages whose row refcount is 0 are RETAINED by the pool
and evicted LRU under allocation pressure (PagePool._evict_lru_locked →
``forget_page`` here → the page's whole trie subtree decommits, since a
child block is meaningless without its prefix).

Locking: every method runs under the pool's RLock (``self._lock`` IS
``pool.lock``); the engine calls match/commit under it.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from symbiont_tpu.kv.pool import PagePool


class _Node:
    __slots__ = ("parent", "key", "page", "children", "logits")

    def __init__(self, parent: Optional["_Node"], key, page: int):
        self.parent = parent
        self.key = key              # the block's token-id tuple
        self.page = page            # pool page backing this block's K/V
        self.children: Dict[tuple, "_Node"] = {}
        self.logits: Optional[np.ndarray] = None  # full-prompt terminal


class Match(NamedTuple):
    """One row's walk result: the committed page per matched block (in
    block order from 0), and — when every prompt block matched and the
    terminal stored logits — the host logits that make the admit a
    FULL hit (prefill skipped entirely)."""

    pages: List[int]
    logits: Optional[np.ndarray]

    @property
    def blocks(self) -> int:
        return len(self.pages)


class RadixCache:
    def __init__(self, pool: PagePool, page_tokens: int):
        self.pool = pool
        self.page = int(page_tokens)
        self._lock = pool.lock
        self._roots: Dict[Tuple[int, int], _Node] = {}  # (P, pad) → root
        self._page_nodes: Dict[int, _Node] = {}
        pool._on_evict = self.forget_page
        self.stats = {"hits": 0, "full_hits": 0, "misses": 0,
                      "committed_pages": 0}

    # ------------------------------------------------------------- matching

    def _blocks(self, row_ids: np.ndarray) -> List[tuple]:
        P = len(row_ids)
        return [tuple(int(t) for t in row_ids[b:b + self.page])
                for b in range(0, P, self.page)]

    def match(self, P: int, pad: int, row_ids: np.ndarray) -> Match:
        """Walk the trie with one right-aligned prompt row [P]. Matched
        pages are LRU-touched but NOT retained — the caller retains
        exactly the pages it wires at splice time (a rejected admit must
        not leak refcounts)."""
        with self._lock:
            node = self._roots.get((P, pad))
            pages: List[int] = []
            for key in self._blocks(row_ids):
                node = node.children.get(key) if node is not None else None
                if node is None:
                    break
                pages.append(node.page)
                self.pool.touch(node.page)
            full = (node is not None and len(pages) == P // self.page
                    and node.logits is not None)
            if pages:
                self.stats["hits"] += 1
            else:
                self.stats["misses"] += 1
            if full:
                self.stats["full_hits"] += 1
            return Match(pages, node.logits if full else None)

    def peek(self, P: int, pad: int, row_ids: np.ndarray) -> int:
        """Side-effect-free prefix probe: how many TOKENS of one
        right-aligned prompt row [P] are currently radix-resident. Unlike
        match(), nothing is LRU-touched and no stats move — this is the
        resume path's warm-vs-cold attribution (a dead worker's committed
        pages may still be live in a surviving replica's trie; the
        `gen.resume_warm` counter reads this probe), not an admission."""
        with self._lock:
            node = self._roots.get((P, pad))
            blocks = 0
            for key in self._blocks(row_ids):
                node = node.children.get(key) if node is not None else None
                if node is None:
                    break
                blocks += 1
            return max(0, blocks * self.page - int(pad))

    # ----------------------------------------------------------- committing

    def commit(self, P: int, pad: int, row_ids: np.ndarray,
               block_pages: List[int],
               logits: Optional[np.ndarray] = None) -> None:
        """Commit one admitted row's prompt blocks. ``block_pages[b]`` is
        the page NOW backing block b in the row's page table (shared pages
        for matched blocks, the row's fresh private pages past the fork).
        New trie nodes adopt the fresh pages (pool.commit → they outlive
        the row); blocks already committed keep their existing page — the
        row's private duplicate stays private and frees with the row."""
        with self._lock:
            root = self._roots.setdefault((P, pad), _Node(None, (), -1))
            node = root
            for b, key in enumerate(self._blocks(row_ids)):
                child = node.children.get(key)
                if child is None:
                    child = _Node(node, key, block_pages[b])
                    node.children[key] = child
                    self.pool.commit(block_pages[b])
                    self._page_nodes[block_pages[b]] = child
                    self.stats["committed_pages"] += 1
                node = child
            if logits is not None:
                node.logits = np.asarray(logits, np.float32).copy()

    # ------------------------------------------------------------- eviction

    def forget_page(self, pid: int) -> None:
        """Evict the trie subtree rooted at pid's node (PagePool LRU
        callback — a block without its prefix is unreachable, so the
        whole subtree decommits with it)."""
        with self._lock:
            node = self._page_nodes.pop(pid, None)
            if node is None:  # already gone (subtree of an earlier evict)
                self.pool.decommit(pid)
                return
            if node.parent is not None:
                node.parent.children.pop(node.key, None)
            stack = [node]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                n.children.clear()
                self._page_nodes.pop(n.page, None)
                self.stats["committed_pages"] -= 1
                self.pool.decommit(n.page)

    def clear(self) -> None:
        """Drop every committed prefix (params swap: cached K/V and stored
        logits are stale against the new weights)."""
        with self._lock:
            for pid in list(self._page_nodes):
                self.forget_page(pid)
            self._roots.clear()
