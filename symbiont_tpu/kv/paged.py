"""PagedKVCache — the third KV-cache layout (after the dense ``KVCache``
and int8 ``QuantKVCache`` in models/gpt.py).

K/V live in a preallocated device POOL of fixed-size pages,
``[L, n_pages, page, kv_heads, head_dim]``, and each batch row maps its
cache-index space onto pool pages through a small per-session page table
``[B, n_blocks]`` (block b covers cache slots ``[b·page, (b+1)·page)``).
The LOGICAL cache-index space is identical to the dense layout — prompts
stay right-aligned, every row shares the scalar ``length``, causality and
kv_valid masks are unchanged — pages only add physical indirection. The
attention kernel gathers the pool through the page table into exactly the
``[B, T, kv_heads, head_dim]`` tensor the dense path reads, element for
element, which is what makes paged decode TOKEN-IDENTICAL to dense decode
(the hard gate in tests/test_kv_paged.py) for both kv_quant modes.

Page 0 is a SCRATCH sink: rows with nothing mapped at a block (padding
rows, freed rows, not-yet-allocated decode blocks) point there. Writes to
scratch are harmless garbage; reads from scratch are always masked —
either by causality (future blocks), kv_valid (gap/padding slots), or
because the row's output is discarded (padding rows).

Field conventions match the other two layouts where they matter: the
scalar ``length`` is last, so the decode scan's ``_replace(length=...)``
and the donation-carrying chunk loop treat all three shapes uniformly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

SCRATCH_PAGE = 0  # reserved sink page; never allocated, never trusted


class PagedKVCache(NamedTuple):
    """Pool arrays + page table + the dense-compatible scalar length.

    ``k``/``v``: [L, n_pages, page, kv_heads, head_dim] (model dtype, or
    int8 when composed with kv_quant=int8). ``k_scale``/``v_scale``: f32
    [L, n_pages, page, kv_heads] scale pools (zero-size n_pages axis when
    unquantized, so one NamedTuple covers both compositions).
    ``page_table``: [B, n_blocks] int32 into the pool's page axis.
    ``length``: [] int32 — same semantics as the dense layouts."""

    k: jax.Array
    v: jax.Array
    k_scale: jax.Array
    v_scale: jax.Array
    page_table: jax.Array
    length: jax.Array

    @property
    def page_tokens(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k.dtype == jnp.int8


def init_pool_arrays(num_layers: int, n_pages: int, page: int,
                     kv_heads: int, head_dim: int, dtype,
                     quantized: bool):
    """Zeroed device pools (k, v, k_scale, v_scale). Zeros matter: scratch
    reads before any write must be finite (they multiply exactly-zero
    masked attention probabilities)."""
    shape = (num_layers, n_pages, page, kv_heads, head_dim)
    sshape = (num_layers, n_pages, page, kv_heads)
    if quantized:
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros(sshape, jnp.float32),
                jnp.zeros(sshape, jnp.float32))
    empty = (num_layers, 0, page, kv_heads)
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
            jnp.zeros(empty, jnp.float32), jnp.zeros(empty, jnp.float32))


def flat_slot_index(page_table: jax.Array, slots: jax.Array,
                    page: int) -> jax.Array:
    """Cache slots [S] → flat pool indices [B, S] over the flattened
    (n_pages·page) token axis, through the page table."""
    blocks = slots // page
    offs = slots % page
    pids = jnp.take(page_table, blocks, axis=1)  # [B, S]
    return pids * page + offs[None, :]


@partial(jax.jit, static_argnames=("prompt_width",),
         donate_argnames=("pool_k", "pool_v", "pool_ks", "pool_vs"))
def scatter_prompt(pool_k, pool_v, pool_ks, pool_vs, staged,
                   page_table_b, prompt_width: int):
    """Adopt a dense-staged prefill into the pool: scatter every staged
    row's prompt region [0, prompt_width) into the pages its page-table
    row maps. One scatter per field across all layers (layer offsets are
    folded into the flat index). ``page_table_b`` is the SCATTER table,
    not the row's real page table: it maps only the row's FRESH blocks,
    with radix-shared blocks (and whole non-admitted rows) pointed at the
    scratch sink — committed page content is immutable, because other
    live sessions are reading those pages and a recomputed value is not
    guaranteed bitwise-equal across batch shapes.

    ``staged`` is a dense KVCache or QuantKVCache (models/gpt.py); the
    pools are DONATED (they are the multi-GB resident buffers — the
    engine reassigns from the return at every call site)."""
    L, NP, page = pool_k.shape[0], pool_k.shape[1], pool_k.shape[2]
    P = prompt_width
    slots = jnp.arange(P, dtype=jnp.int32)
    flat = flat_slot_index(page_table_b, slots, page)          # [B2, P]
    lflat = flat[None] + (jnp.arange(L, dtype=jnp.int32)
                          * NP * page)[:, None, None]          # [L, B2, P]

    def scat(pool, vals):
        tok_shape = (L * NP * page,) + pool.shape[3:]
        return pool.reshape(tok_shape).at[lflat].set(
            vals.astype(pool.dtype)).reshape(pool.shape)

    # staged fields: k/v [L, B2, T, kvh, hd] (+ scale planes when int8)
    pool_k = scat(pool_k, staged.k[:, :, :P])
    pool_v = scat(pool_v, staged.v[:, :, :P])
    if pool_ks.shape[1] > 0:  # int8 composition: scale pools ride along
        pool_ks = scat(pool_ks, staged.k_scale[:, :, :P])
        pool_vs = scat(pool_vs, staged.v_scale[:, :, :P])
    return pool_k, pool_v, pool_ks, pool_vs


@partial(jax.jit, static_argnames=("prompt_width",))
def merge_row_state(logits_a, pos_a, done_a, kv_valid_a,
                    logits_b, pos_b, done_b, kv_valid_b,
                    row_map, length, prompt_width: int):
    """The row-state half of a paged splice: pick logits/pos/done/kv_valid
    rows from the prepared state by row_map, with the same gap-masking
    contract as gpt.merge_rows (cache slots [prompt_width, length) — the
    steps the session decoded before this admission — stay invalid for
    spliced rows forever). The CACHE half happens in the pool
    (scatter_prompt + host page-table updates), so nothing here is
    donation-sized."""
    B = logits_a.shape[0]
    T = kv_valid_a.shape[1]
    sel = row_map >= 0
    j = jnp.clip(row_map, 0, logits_b.shape[0] - 1)

    def pick(a, b):
        take = jnp.take(b, j, axis=0)
        shape = [1] * a.ndim
        shape[0] = B
        return jnp.where(sel.reshape(shape), take, a)

    t_idx = jnp.arange(T)
    gap = (t_idx >= prompt_width) & (t_idx < length)
    kv_b = kv_valid_b & ~gap[None, :]
    return (pick(logits_a, logits_b), pick(pos_a, pos_b),
            pick(done_a, done_b), pick(kv_valid_a, kv_b))
