"""PagePool — host-side allocator over one preallocated device page pool.

The pool owns the device arrays a ``PagedKVCache`` references (they are
DONATED through every decode chunk / adoption scatter, so the engine
reassigns them here after each device call) plus all host bookkeeping:

- a free list of page ids (page 0 is the scratch sink, never allocated);
- per-page refcounts — the number of live session rows mapping the page;
- the committed set — pages the radix prefix cache (kv/radix.py) retains
  after their refcount drops to zero, so the next admit with the same
  prompt prefix reuses them instead of re-prefilling;
- LRU eviction of committed refcount-0 pages back to the free list when
  an allocation would otherwise fail (``kv.radix_evictions`` counts).

Exports the ``kv.*`` gauge families (docs/OBSERVABILITY.md), dtype-labeled
like the PR 7 ``lm.kv_*`` gauges. ``register_zero_gauges`` registers the
same families at zero on every runner boot — LM enabled or not — so the
``test_obs_doc_drift`` sweep enforces their doc rows mechanically.

Thread-safety: one RLock shared with the radix cache (the engine mutates
both under it); gauge readers take it briefly at scrape time.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from symbiont_tpu.utils.telemetry import Metrics, metrics as _global_metrics

SCRATCH_PAGE = 0

GAUGES = ("kv.pages_free", "kv.pages_live", "kv.page_fragmentation_pct")
COUNTERS = ("kv.radix_hit_tokens_total", "kv.radix_evictions")


def kv_dtype_label(dtype: str, kv_quant: str) -> str:
    """One labeling rule for every kv.* and lm.kv_* family."""
    return "int8" if kv_quant == "int8" else dtype


def register_zero_gauges(dtype: str, kv_quant: str,
                         registry: Optional[Metrics] = None) -> None:
    """Zero-register the kv.* families at boot (the usage.register_zero
    convention) so the doc-drift contract covers them on a stub stack
    that never constructs an LmEngine."""
    reg = registry if registry is not None else _global_metrics
    labels = {"service": "lm", "kv_dtype": kv_dtype_label(dtype, kv_quant)}
    for name in GAUGES:
        # zero-returning CALLBACKS, not gauge_set: a static value would
        # shadow the real readers a later PagePool/LmEngine registers
        # under the same (name, labels) — re-registering a callback
        # replaces it, which is exactly the takeover wanted here
        reg.register_gauge(name, lambda: 0.0, labels=labels)
    for name in COUNTERS:
        reg.inc(name, 0, labels=labels)


class PoolExhausted(RuntimeError):
    """Allocation failed even after evicting every evictable page —
    admission accounting (LmEngine.can_admit) exists to keep sessions
    from ever reaching this."""


class PagePool:
    def __init__(self, num_layers: int, n_pages: int, page_tokens: int,
                 kv_heads: int, head_dim: int, dtype, quantized: bool,
                 dtype_label: str, registry: Optional[Metrics] = None):
        from symbiont_tpu.kv import paged

        if n_pages < 2:
            raise ValueError("kv pool needs >= 2 pages (scratch + one)")
        self.registry = (registry if registry is not None
                         else _global_metrics)
        self.labels = {"service": "lm", "kv_dtype": dtype_label}
        self.n_pages = int(n_pages)
        self.page_tokens = int(page_tokens)
        self.k, self.v, self.k_scale, self.v_scale = paged.init_pool_arrays(
            num_layers, n_pages, page_tokens, kv_heads, head_dim, dtype,
            quantized)
        self.lock = threading.RLock()
        # page 0 is scratch: never on the free list, never refcounted
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._refcount = [0] * n_pages
        self._committed = [False] * n_pages
        # LRU clock over committed refcount-0 pages (the radix-retained
        # set): page id -> last-touch sequence number
        self._retained: Dict[int, int] = {}
        self._touch_seq = 0
        # eviction notifier: the radix cache deregisters the trie path
        # that references an evicted page (set by RadixCache.attach)
        self._on_evict: Optional[Callable[[int], None]] = None
        self._register_gauges()

    # --------------------------------------------------------------- gauges

    def _register_gauges(self) -> None:
        reg = self.registry
        reg.register_weakref_gauge("kv.pages_free", self,
                                   lambda p: p.pages_free,
                                   labels=self.labels)
        reg.register_weakref_gauge("kv.pages_live", self,
                                   lambda p: p.pages_live,
                                   labels=self.labels)
        # fragmentation is engine-computed (it needs per-session token
        # counts the pool cannot see); register a zero placeholder
        # callback the engine's real reader replaces, so a pool without
        # an engine still exports the family
        reg.register_weakref_gauge("kv.page_fragmentation_pct", self,
                                   lambda p: 0.0, labels=self.labels)
        for name in COUNTERS:
            reg.inc(name, 0, labels=self.labels)
        # hbm attribution plane (obs/hbm.py): the pool claims its full
        # preallocated device bytes; the radix-retained slice is an
        # OVERLAY (a view INSIDE the pool claim, reported but excluded
        # from the attribution sum — counting it twice would overstate)
        from symbiont_tpu.obs.hbm import hbm_ledger

        hbm_ledger.claim("kv.page_pool", self, lambda p: p.device_bytes)
        hbm_ledger.claim(
            "kv.radix_retained", self,
            lambda p: int(p.pages_retained * p.device_bytes / p.n_pages),
            overlay=True)

    @property
    def pages_free(self) -> int:
        with self.lock:
            return len(self._free)

    @property
    def pages_live(self) -> int:
        with self.lock:
            return sum(1 for c in self._refcount if c > 0)

    @property
    def pages_retained(self) -> int:
        with self.lock:
            return len(self._retained)

    @property
    def device_bytes(self) -> int:
        return sum(int(a.nbytes) for a in
                   (self.k, self.v, self.k_scale, self.v_scale))

    # ---------------------------------------------------------- device side

    def adopt_arrays(self, k, v, k_scale, v_scale) -> None:
        """Reassign the pool buffers after a donating device call (decode
        chunk / adoption scatter). Caller holds the engine lock — device
        work is serialized there."""
        self.k, self.v, self.k_scale, self.v_scale = k, v, k_scale, v_scale

    # ------------------------------------------------------------ host side

    def can_alloc(self, n: int) -> bool:
        with self.lock:
            return len(self._free) + len(self._retained) >= n

    def alloc(self, n: int = 1) -> List[int]:
        """Take n fresh pages (refcount 1 each), evicting LRU radix-
        retained pages if the free list runs short."""
        with self.lock:
            while len(self._free) < n and self._retained:
                self._evict_lru_locked()
            if len(self._free) < n:
                raise PoolExhausted(
                    f"KV page pool exhausted: need {n}, "
                    f"free {len(self._free)} of {self.n_pages}")
            out = [self._free.pop() for _ in range(n)]
            for pid in out:
                self._refcount[pid] = 1
                self._committed[pid] = False
            return out

    def retain(self, pid: int) -> None:
        """A new row maps an already-materialized (radix-shared) page."""
        with self.lock:
            self._refcount[pid] += 1
            self._retained.pop(pid, None)

    def release(self, pid: int) -> None:
        """A row unmapped the page (finish/cancel). Committed pages are
        retained for radix reuse; uncommitted ones return to the free
        list immediately."""
        with self.lock:
            self._refcount[pid] -= 1
            assert self._refcount[pid] >= 0, f"double release of page {pid}"
            if self._refcount[pid] == 0:
                if self._committed[pid]:
                    self._touch_seq += 1
                    self._retained[pid] = self._touch_seq
                else:
                    self._free.append(pid)

    def commit(self, pid: int) -> None:
        """The radix cache adopted this page (it backs a trie node)."""
        with self.lock:
            self._committed[pid] = True

    def decommit(self, pid: int) -> None:
        """The radix cache dropped this page (eviction / clear)."""
        with self.lock:
            self._committed[pid] = False
            if pid in self._retained:
                del self._retained[pid]
                self._free.append(pid)

    def touch(self, pid: int) -> None:
        """LRU bump on a radix match (even before the admit retains it)."""
        with self.lock:
            if pid in self._retained:
                self._touch_seq += 1
                self._retained[pid] = self._touch_seq

    def _evict_lru_locked(self) -> None:
        pid = min(self._retained, key=self._retained.get)
        if self._on_evict is not None:
            # the radix cache decommits the page's whole trie subtree
            # (which frees pid itself via decommit)
            self._on_evict(pid)
        else:
            self.decommit(pid)
        self.registry.inc("kv.radix_evictions", 1, labels=self.labels)

    def note_hit_tokens(self, n: int) -> None:
        if n > 0:
            self.registry.inc("kv.radix_hit_tokens_total", n,
                              labels=self.labels)
