"""Paged KV subsystem (ROADMAP item 1, the decode-at-the-HBM-limit plane).

Three pieces, layered bottom-up:

- ``kv/paged.py`` — the ``PagedKVCache`` layout (the THIRD cache layout
  next to models/gpt.py's dense ``KVCache`` and int8 ``QuantKVCache``) plus
  the jitted scatter/gather ops the attention kernel and the admission
  splice use. Pure JAX; imports nothing above models/quant.
- ``kv/pool.py`` — the host-side page allocator over one preallocated
  device pool: free list, per-page refcounts, scratch-page sink, the
  ``kv.*`` gauges, and LRU eviction of committed-but-unreferenced pages.
- ``kv/radix.py`` — the refcounted radix prefix cache: a token trie over
  committed prompt pages with copy-on-write forking at divergence, so an
  admit whose prompt hits a cached prefix reuses pages instead of
  re-materializing them, and a full-prompt hit skips prefill entirely.

Wiring lives in engine/lm.py (sessions), models/gpt.py (attention +
merge_rows), and runner.py (boot-time gauge registration). docs/KV.md is
the operator story.
"""

from symbiont_tpu.kv.paged import PagedKVCache  # noqa: F401
