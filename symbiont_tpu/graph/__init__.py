"""Embedded knowledge-graph store (Neo4j-parity semantics, sqlite-backed).

The reference stores graph data in an external Neo4j over Bolt (reference:
services/knowledge_graph_service/src/main.rs). That path is ORPHANED in
v0.3.0 — no producer publishes its input subject (SURVEY.md fact #3). Here the
graph store is embedded in the framework and the producing side is restored
(preprocessing publishes data.processed_text.tokenized).
"""

from symbiont_tpu.graph.store import GraphStore

__all__ = ["GraphStore"]
