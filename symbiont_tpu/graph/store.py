"""Property-graph store with MERGE semantics, persisted in sqlite (stdlib).

Schema parity with the reference's Neo4j usage (reference:
services/knowledge_graph_service/src/main.rs:23-140):

- Document nodes, unique on original_id (constraint ensured at startup,
  main.rs:158-173), MERGE ON CREATE/ON MATCH updates source_url +
  processed_at_ms;
- Sentence nodes unique on text; (d)-[:HAS_SENTENCE {order}]->(s) edges;
  empty sentences skipped (main.rs:70-93);
- Token nodes unique on lowercase text (index on text_lc, main.rs:166-168),
  original case stored/updated as a property; (d)-[:CONTAINS_TOKEN]->(t)
  edges deduped; empty tokens skipped (main.rs:100-125);
- the whole document save is one transaction (main.rs:32-134).

sqlite gives the single-file durability Neo4j volumes gave the reference
(SURVEY.md §5.4 DB-as-truth), without an external server.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from symbiont_tpu.config import GraphStoreConfig
from symbiont_tpu.schema import TokenizedTextMessage

_SCHEMA = """
CREATE TABLE IF NOT EXISTS nodes (
  node_id INTEGER PRIMARY KEY AUTOINCREMENT,
  label TEXT NOT NULL,
  merge_key TEXT NOT NULL,
  props TEXT NOT NULL DEFAULT '{}',
  created_at_ms INTEGER NOT NULL,
  UNIQUE (label, merge_key)
);
CREATE INDEX IF NOT EXISTS idx_nodes_label_key ON nodes(label, merge_key);
CREATE TABLE IF NOT EXISTS edges (
  src INTEGER NOT NULL REFERENCES nodes(node_id),
  dst INTEGER NOT NULL REFERENCES nodes(node_id),
  type TEXT NOT NULL,
  props TEXT NOT NULL DEFAULT '{}',
  UNIQUE (src, dst, type, props)
);
CREATE INDEX IF NOT EXISTS idx_edges_src ON edges(src, type);
"""


class GraphStore:
    def __init__(self, config: Optional[GraphStoreConfig] = None,
                 path: Optional[str] = None):
        self.config = config or GraphStoreConfig()
        if path is None:
            root = Path(self.config.data_dir)
            root.mkdir(parents=True, exist_ok=True)
            path = str(root / "graph.sqlite3")
        self._lock = threading.RLock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self.ensure_schema()

    def ensure_schema(self) -> None:
        """Idempotent constraint/index setup (reference: main.rs:158-173)."""
        with self._lock, self._db:
            self._db.executescript(_SCHEMA)

    # ------------------------------------------------------------ primitives

    def _merge_node(self, cur, label: str, key: str, props: Dict) -> int:
        """MERGE: create with props if absent, else update props (ON MATCH)."""
        now = int(time.time() * 1000)
        row = cur.execute(
            "SELECT node_id, props FROM nodes WHERE label=? AND merge_key=?",
            (label, key)).fetchone()
        if row is None:
            cur.execute(
                "INSERT INTO nodes (label, merge_key, props, created_at_ms) "
                "VALUES (?,?,?,?)",
                (label, key, json.dumps(props, ensure_ascii=False), now))
            return cur.lastrowid
        node_id, old = row
        merged = {**json.loads(old), **props}
        cur.execute("UPDATE nodes SET props=? WHERE node_id=?",
                    (json.dumps(merged, ensure_ascii=False), node_id))
        return node_id

    def _merge_edge(self, cur, src: int, dst: int, etype: str, props: Dict) -> None:
        cur.execute(
            "INSERT OR IGNORE INTO edges (src, dst, type, props) VALUES (?,?,?,?)",
            (src, dst, etype, json.dumps(props, sort_keys=True)))

    # ------------------------------------------------------------- document

    def save_tokenized(self, msg: TokenizedTextMessage) -> int:
        """Single-transaction document save (reference: save_to_neo4j,
        main.rs:23-140). Returns the Document node id."""
        with self._lock, self._db:
            cur = self._db.cursor()
            doc_id = self._merge_node(cur, "Document", msg.original_id, {
                "original_id": msg.original_id,
                "source_url": msg.source_url,
                "processed_at_ms": msg.timestamp_ms,
            })
            for order, sentence in enumerate(msg.sentences):
                if not sentence.strip():
                    continue  # reference: main.rs:71-77
                s_id = self._merge_node(cur, "Sentence", sentence, {"text": sentence})
                self._merge_edge(cur, doc_id, s_id, "HAS_SENTENCE", {"order": order})
            for token in msg.tokens:
                token = token.strip()
                if not token:
                    continue  # reference: main.rs:103-109
                t_id = self._merge_node(cur, "Token", token.lower(), {
                    "text_lc": token.lower(),
                    "text_original_case": token,
                })
                self._merge_edge(cur, doc_id, t_id, "CONTAINS_TOKEN", {})
            return doc_id

    # --------------------------------------------------------------- queries

    def get_document(self, original_id: str) -> Optional[dict]:
        with self._lock:
            row = self._db.execute(
                "SELECT node_id, props FROM nodes WHERE label='Document' "
                "AND merge_key=?", (original_id,)).fetchone()
            if row is None:
                return None
            return {"node_id": row[0], **json.loads(row[1])}

    def document_sentences(self, original_id: str) -> List[str]:
        """Sentences of a document in HAS_SENTENCE order."""
        doc = self.get_document(original_id)
        if doc is None:
            return []
        with self._lock:
            rows = self._db.execute(
                "SELECT n.props, e.props FROM edges e "
                "JOIN nodes n ON n.node_id = e.dst "
                "WHERE e.src=? AND e.type='HAS_SENTENCE'", (doc["node_id"],)
            ).fetchall()
        pairs = [(json.loads(ep).get("order", 0), json.loads(np_)["text"])
                 for np_, ep in rows]
        return [text for _, text in sorted(pairs)]

    def documents_containing_token(self, token: str,
                                   limit: int = 0) -> List[str]:
        """original_ids of documents containing a token (case-insensitive),
        sorted. limit > 0 bounds the rows INSIDE the query — a stopword
        matching the whole corpus must not materialize and sort every
        document id just to be sliced by the caller."""
        q = ("SELECT DISTINCT d.merge_key FROM nodes t "
             "JOIN edges e ON e.dst = t.node_id AND e.type='CONTAINS_TOKEN' "
             "JOIN nodes d ON d.node_id = e.src "
             "WHERE t.label='Token' AND t.merge_key=? "
             "ORDER BY d.merge_key")
        args: tuple = (token.lower(),)
        if limit > 0:
            q += " LIMIT ?"
            args += (limit,)
        with self._lock:
            rows = self._db.execute(q, args).fetchall()
        return [r[0] for r in rows]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out = {}
            for label in ("Document", "Sentence", "Token"):
                out[label] = self._db.execute(
                    "SELECT COUNT(*) FROM nodes WHERE label=?", (label,)).fetchone()[0]
            out["edges"] = self._db.execute("SELECT COUNT(*) FROM edges").fetchone()[0]
            return out

    def close(self) -> None:
        with self._lock:
            self._db.close()
