"""Optional external-Neo4j backend for the knowledge-graph surface.

The framework's default graph store is the embedded sqlite one
(graph/store.py). Deployments migrating from the reference, which writes to
a real Neo4j over Bolt (reference: docker-compose.yml:2-14;
services/knowledge_graph_service/src/main.rs), can keep their graph: set
`graph_store.uri` (or the reference's NEO4J_URI/USER/PASSWORD env aliases)
to the Neo4j **HTTP API** endpoint (http://host:7474) and the runner swaps
this adapter in.

Write parity with the reference's save_to_neo4j (main.rs:23-140), issued as
ONE transactional HTTP request (`/db/{db}/tx/commit`) to match its
single-explicit-transaction behavior (main.rs:32-134):

- MERGE (d:Document {original_id}) ON CREATE/ON MATCH SET source_url,
  processed_at_ms (main.rs:37-63);
- per non-empty sentence: MERGE (s:Sentence {text}), MERGE
  (d)-[:HAS_SENTENCE {order}]->(s) (main.rs:70-93);
- per non-empty token: MERGE (t:Token {text_lc}), SET
  text_original_case, MERGE (d)-[:CONTAINS_TOKEN]->(t) (main.rs:100-125);
- ensure_schema creates the unique constraint + text_lc index with the
  reference's 5×3s retry (main.rs:158-173,253-284).

Speaks stdlib urllib with basic auth — no neo4j driver dependency.
"""

from __future__ import annotations

import base64
import json
import logging
import urllib.request
from typing import Dict, List, Tuple

from symbiont_tpu.config import GraphStoreConfig
from symbiont_tpu.schema import TokenizedTextMessage
from symbiont_tpu.utils.retry import connect_retry

log = logging.getLogger(__name__)


class Neo4jGraphStore:
    def __init__(self, config: GraphStoreConfig,
                 retries: int = 5, retry_delay_s: float = 3.0):
        if not config.uri:
            raise ValueError("Neo4jGraphStore requires graph_store.uri")
        if not config.uri.startswith(("http://", "https://")):
            # the reference's compose uses bolt://host:7687; this adapter
            # speaks the HTTP API — fail fast with the fix, not a retry loop
            raise ValueError(
                f"graph_store.uri must be the Neo4j HTTP endpoint "
                f"(http://host:7474), not {config.uri!r} — the bolt:// "
                f"protocol is not supported")
        self.config = config
        self.base = config.uri.rstrip("/")
        self._auth = base64.b64encode(
            f"{config.user}:{config.password}".encode()).decode()
        self._retries = retries
        self._retry_delay_s = retry_delay_s

    # ------------------------------------------------------------------ http

    def _commit(self, statements: List[Tuple[str, dict]],
                timeout: float = 30.0) -> List[dict]:
        body = {"statements": [{"statement": s, "parameters": p}
                               for s, p in statements]}
        req = urllib.request.Request(
            f"{self.base}/db/{self.config.database}/tx/commit",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": f"Basic {self._auth}"},
            method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            out = json.loads(r.read())
        if out.get("errors"):
            raise RuntimeError(f"neo4j error: {out['errors']}")
        return out.get("results", [])

    # --------------------------------------------------------------- surface

    def ensure_schema(self) -> None:
        """Unique Document.original_id + Token.text_lc index, retried
        (reference: ensure_schema_internal + retry task, main.rs:158-173,
        253-284)."""
        stmts = [
            ("CREATE CONSTRAINT symbiont_doc_id IF NOT EXISTS "
             "FOR (d:Document) REQUIRE d.original_id IS UNIQUE", {}),
            ("CREATE INDEX symbiont_token_lc IF NOT EXISTS "
             "FOR (t:Token) ON (t.text_lc)", {}),
        ]

        def attempt() -> None:
            for s in stmts:
                self._commit([s])
            log.info("neo4j schema ensured at %s", self.base)

        connect_retry(attempt, retries=self._retries,
                      delay_s=self._retry_delay_s,
                      what=f"neo4j at {self.base}")

    def save_tokenized(self, msg: TokenizedTextMessage) -> int:
        """One transactional commit per document (main.rs:32-134). Returns
        the Document node's internal id."""
        stmts: List[Tuple[str, dict]] = [(
            "MERGE (d:Document {original_id: $original_id}) "
            "ON CREATE SET d.source_url = $source_url, "
            "d.processed_at_ms = $ts "
            "ON MATCH SET d.source_url = $source_url, "
            "d.processed_at_ms = $ts "
            "RETURN id(d)",
            {"original_id": msg.original_id, "source_url": msg.source_url,
             "ts": msg.timestamp_ms})]
        for order, sentence in enumerate(msg.sentences):
            if not sentence.strip():
                continue  # reference: main.rs:71-77
            stmts.append((
                # order inside the MERGE pattern (reference main.rs:82-88):
                # the same sentence text at two positions keeps two edges
                "MATCH (d:Document {original_id: $original_id}) "
                "MERGE (s:Sentence {text: $text}) "
                "MERGE (d)-[r:HAS_SENTENCE {order: $order}]->(s)",
                {"original_id": msg.original_id, "text": sentence,
                 "order": order}))
        for token in msg.tokens:
            token = token.strip()
            if not token:
                continue  # reference: main.rs:103-109
            stmts.append((
                "MATCH (d:Document {original_id: $original_id}) "
                "MERGE (t:Token {text_lc: $lc}) "
                "SET t.text_original_case = $orig "
                "MERGE (d)-[:CONTAINS_TOKEN]->(t)",
                {"original_id": msg.original_id, "lc": token.lower(),
                 "orig": token}))
        results = self._commit(stmts)
        try:
            return int(results[0]["data"][0]["row"][0])
        except (IndexError, KeyError, TypeError, ValueError):
            return -1

    def counts(self) -> Dict[str, int]:
        rows = self._commit([
            ("MATCH (d:Document) RETURN count(d)", {}),
            ("MATCH (s:Sentence) RETURN count(s)", {}),
            ("MATCH (t:Token) RETURN count(t)", {}),
        ])

        def first(i):
            try:
                return int(rows[i]["data"][0]["row"][0])
            except (IndexError, KeyError, TypeError, ValueError):
                return 0

        return {"Document": first(0), "Sentence": first(1), "Token": first(2)}

    def close(self) -> None:  # HTTP is stateless
        pass


def make_graph_store(config: GraphStoreConfig, resilience=None):
    """Backend selection: uri set → external Neo4j; else embedded sqlite.

    With a ResilienceConfig (and breakers enabled), the EXTERNAL backend is
    wrapped in a circuit breaker + document spill (resilience/stores.py):
    a mid-run Neo4j outage spools save_tokenized payloads locally and
    replays them on recovery instead of dropping them."""
    if config.uri:
        store = Neo4jGraphStore(config)
        if resilience is not None and resilience.breaker_enabled:
            from pathlib import Path

            from symbiont_tpu.resilience.breaker import CircuitBreaker
            from symbiont_tpu.resilience.stores import ResilientGraphStore

            return ResilientGraphStore(
                store,
                breaker=CircuitBreaker(
                    "graph_store",
                    failure_threshold=resilience.breaker_failure_threshold,
                    reset_timeout_s=resilience.breaker_reset_timeout_s),
                spill_path=str(Path(resilience.spill_dir)
                               / "graph.spill.jsonl"))
        return store
    from symbiont_tpu.graph.store import GraphStore

    return GraphStore(config)
