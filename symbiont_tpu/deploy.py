"""Offline deployment-topology validation.

This sandbox has no docker, so deploy/docker-compose.yml can't be *executed*
here — but nearly everything that goes wrong in a compose topology is
statically checkable, and one class of bug is historically load-bearing: the
reference shipped v0.3.0 with `knowledge_graph_service` subscribed to a
subject NO service publishes (reference: knowledge_graph_service/src/main.rs:9,
CHANGELOG.md:57-60 — the orphaned `data.processed_text.tokenized`). The
orphan check below makes that bug class impossible to ship in a compose file.

Checks:
  1. YAML parses; every service has image or build; build dockerfiles exist.
  2. Native-image `command:` entrypoints name real native binaries.
  3. Every SYMBIONT_* env var matches a real config field (catches typos —
     the config system ignores unknown env, so a typo'd var silently noops).
  4. depends_on targets exist.
  5. Subject topology: every consumed bus subject has a producer and vice
     versa, derived from the role→subject tables mirroring SURVEY.md §1-L3.

Usage:  python -m symbiont_tpu.deploy deploy/docker-compose.yml
Exit 0 clean, 1 with one problem per line on stderr.
"""

from __future__ import annotations

import dataclasses
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

from symbiont_tpu import subjects as S

NATIVE_BINARIES = {"symbus_broker", "perception", "preprocessing",
                   "vector_memory", "knowledge_graph", "text_generator",
                   "api_gateway"}

# role → (produces, consumes) over pipeline + request-reply subjects
# (request-reply: the requester "consumes" the service's reply inline, so the
# responder side is modeled as the producer of the reply service).
_PIPELINE: Dict[str, Tuple[Set[str], Set[str]]] = {
    "gateway": ({S.TASKS_PERCEIVE_URL, S.TASKS_GENERATION_TEXT},
                {S.EVENTS_TEXT_GENERATED}),
    "perception": ({S.DATA_RAW_TEXT_DISCOVERED}, {S.TASKS_PERCEIVE_URL}),
    "preprocessing": ({S.DATA_TEXT_WITH_EMBEDDINGS,
                       S.DATA_PROCESSED_TEXT_TOKENIZED},
                      {S.DATA_RAW_TEXT_DISCOVERED}),
    "vector_memory": (set(), {S.DATA_TEXT_WITH_EMBEDDINGS}),
    "knowledge_graph": (set(), {S.DATA_PROCESSED_TEXT_TOKENIZED}),
    "text_generator": ({S.EVENTS_TEXT_GENERATED}, {S.TASKS_GENERATION_TEXT}),
    # the engine plane serves request-reply only (engine.*): no pipeline edges
    "engine": (set(), set()),
}

# compose service name / runner service name → topology role
_ROLE_BY_NAME = {"gateway": "gateway", "api": "gateway",
                 "api_gateway": "gateway"}


def _known_env_keys() -> Set[str]:
    """Every env var the config layer actually reads (canonical + aliases),
    plus process-level vars consumed outside the config tree."""
    from symbiont_tpu.config import _ENV_ALIASES, SymbiontConfig

    cfg = SymbiontConfig()
    keys = set(_ENV_ALIASES)
    for section_field in dataclasses.fields(cfg):
        section = getattr(cfg, section_field.name)
        for f in dataclasses.fields(section):
            keys.add(f"SYMBIONT_{section_field.name.upper()}_{f.name.upper()}")
    # read directly by services/tools, not through the config tree
    keys |= {"SYMBIONT_BUS_URL", "SYMBIONT_FRONTEND_PATH",
             "SYMBIONT_COORDINATOR", "SYMBIONT_NUM_PROCESSES",
             "SYMBIONT_PROCESS_ID", "SYMBIONT_MODEL_DIR"}
    return keys


def _env_dict(svc: dict) -> Dict[str, str]:
    """Normalize compose `environment:` — both the list form
    (["KEY=value", ...]) and the mapping form ({KEY: value}) are valid
    compose syntax and must be validated identically."""
    env = svc.get("environment") or {}
    if isinstance(env, dict):
        return {str(k): "" if v is None else str(v) for k, v in env.items()}
    out: Dict[str, str] = {}
    for e in env:
        if isinstance(e, str):
            k, _, v = e.partition("=")
            out[k] = v
    return out


def _service_roles(name: str, svc: dict) -> List[str]:
    """Topology roles a compose service plays."""
    cmd = svc.get("command") or []
    entry = cmd[0] if isinstance(cmd, list) and cmd else (
        cmd.split()[0] if isinstance(cmd, str) and cmd else "")
    if entry in _PIPELINE:
        return [entry]
    if entry in _ROLE_BY_NAME:
        return [_ROLE_BY_NAME[entry]]
    # python runner container: roles from SYMBIONT_RUNNER_SERVICES
    wanted = _env_dict(svc).get("SYMBIONT_RUNNER_SERVICES")
    if wanted:
        if wanted == "all":
            return [r for r in _PIPELINE]
        return [_ROLE_BY_NAME.get(w.strip(), w.strip())
                for w in wanted.split(",") if w.strip()]
    if name in _PIPELINE or name in _ROLE_BY_NAME:
        return [_ROLE_BY_NAME.get(name, name)]
    return []


def validate_compose(path: str | Path) -> List[str]:
    import yaml

    path = Path(path)
    problems: List[str] = []
    try:
        doc = yaml.safe_load(path.read_text())
    except yaml.YAMLError as e:
        return [f"YAML parse error: {e}"]
    services = (doc or {}).get("services")
    if not isinstance(services, dict) or not services:
        return ["no services defined"]

    known_env = _known_env_keys()
    roles: List[str] = []
    for name, svc in services.items():
        svc = svc or {}
        build, image = svc.get("build"), svc.get("image")
        if not build and not image:
            problems.append(f"{name}: neither build nor image")
        if build:
            # string form `build: <context>` is compose shorthand for
            # context-only with Dockerfile at the context root
            if isinstance(build, str):
                build = {"context": build}
            ctx = (path.parent / build.get("context", ".")).resolve()
            df = ctx / build.get("dockerfile", "Dockerfile")
            if not df.exists():
                problems.append(f"{name}: dockerfile {df} does not exist")
        cmd = svc.get("command") or []
        entry = cmd[0] if isinstance(cmd, list) and cmd else (
            cmd.split()[0] if isinstance(cmd, str) and cmd else "")
        if build and entry and entry not in NATIVE_BINARIES \
                and entry not in ("python", "python3"):
            problems.append(f"{name}: command {entry!r} is not a native "
                            f"binary ({sorted(NATIVE_BINARIES)}) or python")
        for key in _env_dict(svc):
            if key.startswith("SYMBIONT_") and key not in known_env:
                problems.append(f"{name}: unknown env var {key} "
                                "(typo? config would silently ignore it)")
        deps = svc.get("depends_on") or {}
        dep_names = deps if isinstance(deps, list) else list(deps)
        for d in dep_names:
            if d not in services:
                problems.append(f"{name}: depends_on unknown service {d!r}")
        if not svc.get("profiles"):  # optional-profile services excluded
            roles.extend(_service_roles(name, svc))

    # subject orphan check over the default-profile topology
    produced: Set[str] = set()
    consumed: Set[str] = set()
    for r in roles:
        if r in _PIPELINE:
            p, c = _PIPELINE[r]
            produced |= p
            consumed |= c
    for subj in sorted(consumed - produced):
        problems.append(f"orphaned subject: {subj} is consumed but no "
                        "service in the topology produces it "
                        "(the reference's v0.3.0 knowledge-graph bug class)")
    for subj in sorted(produced - consumed):
        problems.append(f"dead-end subject: {subj} is produced but no "
                        "service in the topology consumes it")
    return problems


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    problems = validate_compose(argv[0])
    for p in problems:
        print(f"TOPOLOGY: {p}", file=sys.stderr)
    if not problems:
        print(f"{argv[0]}: topology OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
