"""Offline deployment-topology validation.

This sandbox has no docker, so deploy/docker-compose.yml can't be *executed*
here — but nearly everything that goes wrong in a compose topology is
statically checkable, and one class of bug is historically load-bearing: the
reference shipped v0.3.0 with `knowledge_graph_service` subscribed to a
subject NO service publishes (reference: knowledge_graph_service/src/main.rs:9,
CHANGELOG.md:57-60 — the orphaned `data.processed_text.tokenized`). The
orphan check below makes that bug class impossible to ship in a compose file.

Checks:
  1. YAML parses; every service has image or build; build dockerfiles exist.
  2. Native-image `command:` entrypoints name real native binaries.
  3. Every SYMBIONT_* env var matches a real config field (catches typos —
     the config system ignores unknown env, so a typo'd var silently noops).
  4. depends_on targets exist.
  5. Subject topology: every consumed bus subject has a producer and vice
     versa, derived from the role→subject tables mirroring SURVEY.md §1-L3.

Usage:  python -m symbiont_tpu.deploy deploy/docker-compose.yml
Exit 0 clean, 1 with one problem per line on stderr.
"""

from __future__ import annotations

import dataclasses
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

from symbiont_tpu import subjects as S

NATIVE_BINARIES = {"symbus_broker", "perception", "preprocessing",
                   "vector_memory", "knowledge_graph", "text_generator",
                   "api_gateway"}

# role → (produces, consumes) over pipeline + request-reply subjects
# (request-reply: the requester "consumes" the service's reply inline, so the
# responder side is modeled as the producer of the reply service).
_PIPELINE: Dict[str, Tuple[Set[str], Set[str]]] = {
    "gateway": ({S.TASKS_PERCEIVE_URL, S.TASKS_GENERATION_TEXT},
                {S.EVENTS_TEXT_GENERATED}),
    "perception": ({S.DATA_RAW_TEXT_DISCOVERED}, {S.TASKS_PERCEIVE_URL}),
    "preprocessing": ({S.DATA_TEXT_WITH_EMBEDDINGS,
                       S.DATA_PROCESSED_TEXT_TOKENIZED},
                      {S.DATA_RAW_TEXT_DISCOVERED}),
    "vector_memory": (set(), {S.DATA_TEXT_WITH_EMBEDDINGS}),
    "knowledge_graph": (set(), {S.DATA_PROCESSED_TEXT_TOKENIZED}),
    "text_generator": ({S.EVENTS_TEXT_GENERATED}, {S.TASKS_GENERATION_TEXT}),
    # the engine plane serves request-reply only (engine.*): no pipeline edges
    "engine": (set(), set()),
}

# compose service name / runner service name → topology role
_ROLE_BY_NAME = {"gateway": "gateway", "api": "gateway",
                 "api_gateway": "gateway"}


def _known_env_keys() -> Set[str]:
    """Every env var the config layer actually reads (canonical + aliases),
    plus process-level vars consumed outside the config tree."""
    from symbiont_tpu.config import _ENV_ALIASES, SymbiontConfig

    cfg = SymbiontConfig()
    keys = set(_ENV_ALIASES)
    for section_field in dataclasses.fields(cfg):
        section = getattr(cfg, section_field.name)
        for f in dataclasses.fields(section):
            keys.add(f"SYMBIONT_{section_field.name.upper()}_{f.name.upper()}")
    # read directly by services/tools, not through the config tree
    keys |= {"SYMBIONT_BUS_URL", "SYMBIONT_FRONTEND_PATH",
             "SYMBIONT_COORDINATOR", "SYMBIONT_NUM_PROCESSES",
             "SYMBIONT_PROCESS_ID", "SYMBIONT_MODEL_DIR"}
    return keys


def _env_dict(svc: dict) -> Dict[str, str]:
    """Normalize compose `environment:` — both the list form
    (["KEY=value", ...]) and the mapping form ({KEY: value}) are valid
    compose syntax and must be validated identically."""
    env = svc.get("environment") or {}
    if isinstance(env, dict):
        return {str(k): "" if v is None else str(v) for k, v in env.items()}
    out: Dict[str, str] = {}
    for e in env:
        if isinstance(e, str):
            k, _, v = e.partition("=")
            out[k] = v
    return out


def _service_roles(name: str, svc: dict) -> List[str]:
    """Topology roles a compose service plays."""
    cmd = svc.get("command") or []
    entry = cmd[0] if isinstance(cmd, list) and cmd else (
        cmd.split()[0] if isinstance(cmd, str) and cmd else "")
    if entry in _PIPELINE:
        return [entry]
    if entry in _ROLE_BY_NAME:
        return [_ROLE_BY_NAME[entry]]
    # python runner container: roles from SYMBIONT_RUNNER_SERVICES
    wanted = _env_dict(svc).get("SYMBIONT_RUNNER_SERVICES")
    if wanted:
        if wanted == "all":
            return [r for r in _PIPELINE]
        return [_ROLE_BY_NAME.get(w.strip(), w.strip())
                for w in wanted.split(",") if w.strip()]
    if name in _PIPELINE or name in _ROLE_BY_NAME:
        return [_ROLE_BY_NAME.get(name, name)]
    return []


def validate_compose(path: str | Path) -> List[str]:
    import yaml

    path = Path(path)
    problems: List[str] = []
    try:
        doc = yaml.safe_load(path.read_text())
    except yaml.YAMLError as e:
        return [f"YAML parse error: {e}"]
    services = (doc or {}).get("services")
    if not isinstance(services, dict) or not services:
        return ["no services defined"]

    known_env = _known_env_keys()
    roles: List[str] = []
    for name, svc in services.items():
        svc = svc or {}
        build, image = svc.get("build"), svc.get("image")
        if not build and not image:
            problems.append(f"{name}: neither build nor image")
        if build:
            # string form `build: <context>` is compose shorthand for
            # context-only with Dockerfile at the context root
            if isinstance(build, str):
                build = {"context": build}
            ctx = (path.parent / build.get("context", ".")).resolve()
            df = ctx / build.get("dockerfile", "Dockerfile")
            if not df.exists():
                problems.append(f"{name}: dockerfile {df} does not exist")
        cmd = svc.get("command") or []
        entry = cmd[0] if isinstance(cmd, list) and cmd else (
            cmd.split()[0] if isinstance(cmd, str) and cmd else "")
        if build and entry and entry not in NATIVE_BINARIES \
                and entry not in ("python", "python3"):
            problems.append(f"{name}: command {entry!r} is not a native "
                            f"binary ({sorted(NATIVE_BINARIES)}) or python")
        for key in _env_dict(svc):
            if key.startswith("SYMBIONT_") and key not in known_env:
                problems.append(f"{name}: unknown env var {key} "
                                "(typo? config would silently ignore it)")
        deps = svc.get("depends_on") or {}
        dep_names = deps if isinstance(deps, list) else list(deps)
        for d in dep_names:
            if d not in services:
                problems.append(f"{name}: depends_on unknown service {d!r}")
        if not svc.get("profiles"):  # optional-profile services excluded
            roles.extend(_service_roles(name, svc))

    # subject orphan check over the default-profile topology
    produced: Set[str] = set()
    consumed: Set[str] = set()
    for r in roles:
        if r in _PIPELINE:
            p, c = _PIPELINE[r]
            produced |= p
            consumed |= c
    for subj in sorted(consumed - produced):
        problems.append(f"orphaned subject: {subj} is consumed but no "
                        "service in the topology produces it "
                        "(the reference's v0.3.0 knowledge-graph bug class)")
    for subj in sorted(produced - consumed):
        problems.append(f"dead-end subject: {subj} is produced but no "
                        "service in the topology consumes it")
    return problems


# ---------------------------------------------------------------------------
# Live-store compatibility check (VERDICT r4 next-5)
#
# The adapters (memory/qdrant_backend.py, graph/neo4j_backend.py) are
# validated offline against recorded wire fixtures and fake servers — but the
# reference runs REAL Qdrant/Neo4j. This suite is the one-command check a
# migrating deployment runs against its live stores on first deployment:
#
#   python -m symbiont_tpu.deploy --compat qdrant=http://host:6333 \
#                                          neo4j=http://host:7474
#
# Neo4j credentials ride the reference's env aliases (NEO4J_USER /
# NEO4J_PASSWORD) or SYMBIONT_GRAPH_USER / SYMBIONT_GRAPH_PASSWORD.
# Every check runs in a throwaway namespace (fresh collection name /
# namespaced document ids) and cleans up after itself — safe against a
# store that also holds production data.
# ---------------------------------------------------------------------------


def _qdrant_compat(uri: str, say) -> List[str]:
    import os
    import time
    import urllib.error

    import numpy as np

    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.qdrant_backend import QdrantStore

    failures: List[str] = []

    def check(name: str, fn) -> None:
        try:
            fn()
            say(f"  ok   qdrant: {name}")
        except Exception as e:
            failures.append(f"qdrant: {name}: {e}")
            say(f"  FAIL qdrant: {name}: {e}")

    coll = f"symbiont_compat_{os.getpid()}_{int(time.time())}"
    dim = 384
    cfg = VectorStoreConfig(uri=uri, dim=dim, collection=coll)
    store = QdrantStore(cfg, retries=2, retry_delay_s=1.0)
    rng = np.random.default_rng(0)

    check("connect + create collection (dim 384, cosine)",
          store.ensure_collection)
    check("ensure is idempotent", store.ensure_collection)

    def dim_conflict():
        other = QdrantStore(VectorStoreConfig(uri=uri, dim=128,
                                              collection=coll),
                            retries=1, retry_delay_s=0.1)
        try:
            other.ensure_collection()
        except ValueError:
            return  # expected: fail-fast on dim mismatch
        raise AssertionError("dim-mismatched ensure did not fail fast")
    check("dim conflict fails fast with a typed error", dim_conflict)

    vecs = rng.normal(size=(8, dim)).astype(np.float32)
    payload = {"sentence_text": "héllo wörld — 多言語", "sentence_order": 1,
               "model_name": "compat", "nested": {"k": [1, 2, 3]}}
    pts = [(f"00000000-0000-4000-8000-{i:012d}", vecs[i], dict(payload))
           for i in range(8)]

    def small_roundtrip():
        assert store.upsert(pts) == 8
        assert store.count() == 8, store.count()
        hits = store.search(vecs[3], 3)
        assert hits and hits[0].id == pts[3][0], hits
        assert hits[0].score > 0.99, hits[0].score
        assert hits[0].payload["sentence_text"] == payload["sentence_text"]
        assert hits[0].payload["nested"] == payload["nested"]
    check("upsert + exact count + self-match search + unicode payload "
          "round-trip", small_roundtrip)

    big_n = 1100  # 3 chunks of UPSERT_CHUNK=512; >10 MB of JSON total
    big = [(f"00000000-0000-4000-9000-{i:012d}",
            rng.normal(size=dim).astype(np.float32),
            {"sentence_text": "x" * 4096, "sentence_order": i})
           for i in range(big_n)]

    def big_upsert():
        assert store.upsert(big) == big_n
        assert store.count() == 8 + big_n, store.count()
    check(f"chunked >10MB upsert ({big_n} points, wait=true)", big_upsert)

    def idempotent():
        store.upsert(pts)
        assert store.count() == 8 + big_n, store.count()
    check("re-upsert of same ids is idempotent (no duplicates)", idempotent)

    def error_shape():
        ghost = QdrantStore(VectorStoreConfig(uri=uri, dim=dim,
                                              collection=coll + "_missing"),
                            retries=1, retry_delay_s=0.1)
        try:
            ghost.search(vecs[0], 1)
        except urllib.error.HTTPError:
            return  # expected: surfaced as a typed HTTP error
        raise AssertionError("search on a missing collection did not error")
    check("missing-collection search surfaces an HTTP error", error_shape)

    def cleanup():
        store._call("DELETE", f"/collections/{coll}")
    check("cleanup: delete compat collection", cleanup)
    return failures


def _neo4j_compat(uri: str, say) -> List[str]:
    import os
    import time

    from symbiont_tpu.config import GraphStoreConfig
    from symbiont_tpu.graph.neo4j_backend import Neo4jGraphStore
    from symbiont_tpu.schema import TokenizedTextMessage

    failures: List[str] = []

    def check(name: str, fn) -> None:
        try:
            fn()
            say(f"  ok   neo4j: {name}")
        except Exception as e:
            failures.append(f"neo4j: {name}: {e}")
            say(f"  FAIL neo4j: {name}: {e}")

    user = (os.environ.get("SYMBIONT_GRAPH_USER")
            or os.environ.get("NEO4J_USER") or "neo4j")
    password = (os.environ.get("SYMBIONT_GRAPH_PASSWORD")
                or os.environ.get("NEO4J_PASSWORD") or "")
    store = Neo4jGraphStore(GraphStoreConfig(uri=uri, user=user,
                                             password=password),
                            retries=2, retry_delay_s=1.0)
    ns = f"symbiont-compat-{os.getpid()}-{int(time.time())}"

    check("connect + ensure schema (constraint + index)", store.ensure_schema)
    check("ensure_schema is idempotent", store.ensure_schema)

    msg = TokenizedTextMessage(
        original_id=f"{ns}-doc-1", source_url="http://compat",
        sentences=["Première phrase — 多言語.", "  ", "Second one."],
        tokens=["Alpha", "beta", " ", "ALPHA", "多言語"],
        timestamp_ms=int(time.time() * 1000))

    def save():
        doc_id = store.save_tokenized(msg)
        assert isinstance(doc_id, int), doc_id
    check("save_tokenized (unicode, skip-empty, MERGE semantics)", save)
    check("re-save of the same document is idempotent (MERGE)", save)

    big = TokenizedTextMessage(
        original_id=f"{ns}-doc-big", source_url="http://compat",
        sentences=[f"Sentence number {i} of the large document."
                   for i in range(200)],
        tokens=[f"token{i}" for i in range(2000)],
        timestamp_ms=int(time.time() * 1000))
    check("large single-transaction save (200 sentences, 2000 tokens)",
          lambda: store.save_tokenized(big))

    def counts():
        c = store.counts()
        assert all(isinstance(v, int) for v in c.values()), c
    check("counts() returns integer node counts", counts)

    def cleanup():
        store._commit([(
            "MATCH (d:Document) WHERE d.original_id STARTS WITH $p "
            "DETACH DELETE d", {"p": ns})])
    check("cleanup: detach-delete compat documents", cleanup)
    return failures


def compat_check(targets: Dict[str, str], say=print) -> List[str]:
    """Run the live-store compat suites for every given target
    ("qdrant"/"neo4j" → base URI). Returns the list of failures."""
    failures: List[str] = []
    for kind, uri in targets.items():
        say(f"compat: {kind} at {uri}")
        if kind == "qdrant":
            failures += _qdrant_compat(uri, say)
        elif kind == "neo4j":
            failures += _neo4j_compat(uri, say)
        else:
            failures.append(f"unknown compat target {kind!r} "
                            "(expected qdrant=... or neo4j=...)")
    return failures


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv or argv[0] in ("--help", "-h"):
        # --help used to fall through to validate_compose("--help") and die
        # with a FileNotFoundError traceback (VERDICT r5 weak #5)
        print(__doc__, file=sys.stderr)
        return 0 if argv else 2
    if argv[0] == "--compat":
        targets: Dict[str, str] = {}
        for arg in argv[1:]:
            if "=" not in arg:
                print(f"--compat arguments must be kind=uri, got {arg!r}",
                      file=sys.stderr)
                return 2
            kind, uri = arg.split("=", 1)
            if kind in targets:
                # silent-overwrite meant `qdrant=A qdrant=B` checked only B
                # while the operator believed both were covered (ADVICE r5)
                print(f"--compat target {kind!r} given twice "
                      f"({targets[kind]!r} then {uri!r}) — pass each kind "
                      "once", file=sys.stderr)
                return 2
            targets[kind] = uri
        if not targets:
            print("--compat needs at least one of qdrant=URI neo4j=URI",
                  file=sys.stderr)
            return 2
        failures = compat_check(targets)
        if failures:
            print(f"{len(failures)} compat check(s) FAILED", file=sys.stderr)
            return 1
        print("all compat checks passed")
        return 0
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    if not Path(argv[0]).exists():
        print(f"compose file {argv[0]!r} does not exist", file=sys.stderr)
        return 2
    problems = validate_compose(argv[0])
    for p in problems:
        print(f"TOPOLOGY: {p}", file=sys.stderr)
    if not problems:
        print(f"{argv[0]}: topology OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
