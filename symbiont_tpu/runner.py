"""Single-process runner: bus + engine + all services.

The reference needs docker-compose with 10 containers to run at all
(reference: docker-compose.yml:1-151); this runner hosts the full pipeline in
one process over the in-proc bus (or any subset against the native broker via
config.bus.url). Usage:

    python -m symbiont_tpu.runner            # full stack, config from env
    SYMBIONT_API_PORT=8080 python -m symbiont_tpu.runner
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Optional

from symbiont_tpu import subjects
from symbiont_tpu.bus import connect
from symbiont_tpu.config import SymbiontConfig, load_config
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.services.api import ApiService
from symbiont_tpu.services.knowledge_graph import KnowledgeGraphService
from symbiont_tpu.services.perception import PerceptionService
from symbiont_tpu.services.preprocessing import PreprocessingService
from symbiont_tpu.services.text_generator import TextGeneratorService
from symbiont_tpu.services.vector_memory import VectorMemoryService

log = logging.getLogger(__name__)


class SymbiontStack:
    """Builds and owns the full service stack; also the e2e-test harness."""

    def __init__(self, config: Optional[SymbiontConfig] = None, bus=None,
                 engine: Optional[TpuEngine] = None, mesh=None,
                 fetcher=None):
        self.config = config or load_config()
        self._bus_override = bus
        self._engine_override = engine
        self._mesh = mesh
        self._fetcher = fetcher
        self.services: list = []
        self.bus = None
        self.engine = None
        self.lm = None
        self._lm_batcher = None
        self.vector_store = None
        self.graph_store = None
        self.api: Optional[ApiService] = None
        self.watchdog = None  # obs.watchdog.SloWatchdog when configured
        self._heartbeat_task: Optional[asyncio.Task] = None
        # drain protocol (resilience/autoscale.py scale-in): flipped by a
        # `_sys.drain.<role>` message from the supervisor; `drained` wakes
        # main() so the process exits once the drain completes
        self.draining = False
        self.drained = asyncio.Event()
        self._drain_sub = None
        self._drain_task: Optional[asyncio.Task] = None
        self._hb_role = ""
        # fleet telemetry plane (obs/fleet.py): the per-role exporter and,
        # in the API-role process, the aggregator behind the federated
        # /metrics + /api/fleet surfaces
        self.fleet_exporter = None
        self.fleet = None

    KNOWN_SERVICES = {"all", "perception", "preprocessing", "vector_memory",
                      "knowledge_graph", "text_generator", "api", "engine"}

    async def start(self) -> None:
        cfg = self.config
        want = {s.strip() for s in cfg.runner.services.split(",") if s.strip()}
        unknown = want - self.KNOWN_SERVICES
        if unknown or not want:
            raise ValueError(
                f"unknown service name(s) {sorted(unknown)} in runner.services; "
                f"known: {sorted(self.KNOWN_SERVICES)}")

        def on(name: str) -> bool:
            return "all" in want or name in want

        # observability plane (symbiont_tpu/obs/): size the flight recorder,
        # apply histogram bucket bounds BEFORE any traffic observes into
        # them, register the standard process_* host gauges, and, when p99
        # thresholds are configured, run the SLO watchdog over the span
        # histograms every service handler feeds
        from symbiont_tpu.obs.device import register_process_gauges
        from symbiont_tpu.obs.engine_timeline import engine_timeline
        from symbiont_tpu.obs.trace_store import trace_store
        from symbiont_tpu.obs.usage import usage
        from symbiont_tpu.utils.telemetry import metrics

        if trace_store.capacity != cfg.obs.trace_capacity:
            trace_store.set_capacity(cfg.obs.trace_capacity)
        # tail-based retention (obs/trace_store.py): errored / SLO-breach /
        # slowest-decile traces pin into a bounded keep-set; healthy
        # traces sample at the configured rate. Gauges read the store's
        # own counters at scrape time (the store cannot import telemetry).
        trace_store.configure_retention(
            sample_rate=cfg.obs.trace_sample_rate,
            keep_traces=cfg.obs.trace_keep_traces)
        metrics.register_gauge("obs.trace_pinned_traces",
                               trace_store.pinned_traces)
        metrics.register_gauge("obs.trace_sampled_out",
                               lambda: trace_store.sampled_out)
        metrics.register_gauge("obs.trace_pin_evicted",
                               lambda: trace_store.pin_evictions)
        # decode-plane flight recorder (obs/engine_timeline.py) + the
        # per-tenant usage ledger (obs/usage.py): sized here, zero-
        # registered so the doc-drift contract covers every family at boot
        engine_timeline.configure(cfg.obs.timeline_capacity,
                                  cfg.obs.timeline_prompt_window)
        metrics.register_gauge("obs.timeline_events",
                               engine_timeline.__len__)
        usage.set_max_tenants(cfg.obs.usage_max_tenants)
        usage.register_zero()
        # compute-plane profiler (obs/xprof.py): size the per-executable
        # dispatch ledger + device-trace capture, then zero-register the
        # xla.dispatches_total / engine.host_syncs_total families so the
        # doc-drift sweep (and /metrics) sees them before any dispatch —
        # one series per allowlisted host-sync site, even if it never fires
        from symbiont_tpu.obs.xprof import device_trace, dispatch_ledger
        dispatch_ledger.configure(enabled=cfg.obs.xprof_enabled,
                                  max_executables=cfg.obs.xprof_executables)
        device_trace.configure(trace_dir=cfg.obs.xprof_trace_dir,
                               max_s=cfg.obs.xprof_trace_max_s)
        dispatch_ledger.register_zero()
        metrics.register_gauge("obs.xprof_executables",
                               dispatch_ledger.__len__)
        # hbm attribution plane (obs/hbm.py): configure the subsystem
        # byte ledger + OOM forensics, zero-register their families for
        # the doc-drift sweep. Per-claim gauges register LATER (after
        # services boot, when the engines have claimed) — see _start's
        # device-gauge block.
        from symbiont_tpu.obs.hbm import hbm_ledger, oom_forensics
        hbm_ledger.configure(enabled=cfg.obs.hbm_enabled,
                             census_groups=cfg.obs.hbm_census_groups)
        oom_forensics.configure(postmortem_dir=cfg.obs.hbm_postmortem_dir,
                                max_files=cfg.obs.hbm_postmortem_max,
                                enabled=cfg.obs.hbm_enabled)
        hbm_ledger.register_zero()
        oom_forensics.register_zero()
        # kv.* page-pool/radix families at zero BEFORE the engine exists
        # (zero-returning callbacks a real PagePool later replaces) — the
        # doc-drift sweep sees them even on a stub stack with no LM
        from symbiont_tpu.kv.pool import register_zero_gauges
        register_zero_gauges(cfg.lm.dtype, cfg.lm.kv_quant)
        if cfg.obs.histogram_buckets_ms:
            metrics.set_bucket_bounds(cfg.obs.histogram_buckets_ms)
        register_process_gauges()  # platform-guarded no-op off Linux
        if cfg.obs.slo_p99_ms:
            from symbiont_tpu.obs.watchdog import SloWatchdog, parse_thresholds

            self.watchdog = SloWatchdog(parse_thresholds(cfg.obs.slo_p99_ms),
                                        interval_s=cfg.obs.slo_interval_s,
                                        burn_fast_s=cfg.obs.slo_burn_fast_s,
                                        burn_slow_s=cfg.obs.slo_burn_slow_s)
            self.watchdog.start()

        self.services = []
        self.bus = self._bus_override or await connect(cfg.bus.url)

        # API gateway starts FIRST (when hosted): liveness (/healthz) and
        # readiness (/readyz → 503) must answer DURING engine placement /
        # mesh build, so a load balancer keeps traffic away from a cold
        # process instead of timing out against a socket that doesn't exist
        # yet. mark_ready() flips only at the very end of start(), once
        # params are placed and the mesh (when parallel.enabled) is built.
        if on("api"):
            admission_ctl = ladder = None
            if cfg.admission.enabled:
                from symbiont_tpu.resilience.admission import (
                    AdmissionController,
                    DegradationLadder,
                )

                admission_ctl = AdmissionController(cfg.admission)
                # SLO-aware shedding: the watchdog's breach passes drive
                # the degradation ladder the gateway consults per request
                ladder = DegradationLadder(
                    recovery_passes=cfg.admission.shed_recovery_passes,
                    hold_s=cfg.admission.shed_hold_s,
                    degraded_top_k=cfg.admission.degraded_top_k)
                if self.watchdog is not None:
                    self.watchdog.add_listener(ladder.on_slo_pass)
            self.api = ApiService(
                self.bus, cfg.api, cfg.bus,
                admission=admission_ctl, ladder=ladder,
                # capacity-aware generation admission: consult the live
                # LM's KV-row occupancy before accepting a stream (late-
                # bound — the LM is constructed below)
                gen_capacity=(
                    (lambda: self.lm is None
                     or self.lm.can_admit(1, cfg.admission.max_kv_rows))
                    if cfg.admission.enabled else None),
                admission_config=(cfg.admission if cfg.admission.enabled
                                  else None),
                defer_ready=True)
            await self.api.start()

        # Multi-chip serving plane (ROADMAP item 1): the mesh is a first-
        # class, config-driven property of the live stack. When this process
        # is about to construct a real device engine (embed or LM) and no
        # caller handed a mesh in, build one from cfg.parallel —
        # mesh_shape unset means all local devices on the 'data' axis, so a
        # multi-chip host serves DP out of the box and a single-chip host
        # gets an inert (1, 1) mesh with byte-identical executables. The
        # same mesh reaches the vector store (corpus rows shard over 'data')
        # and LmEngine (TP decode when 'tensor' > 1). Stub-engine test
        # stacks (engine override) skip it: no real device work, no mesh.
        builds_real_engine = (
            self._engine_override is None
            and (on("preprocessing") or on("engine")))
        builds_real_lm = cfg.lm.enabled and (on("text_generator")
                                             or on("engine"))
        # a standalone vector_memory worker (store in this process, engine
        # elsewhere) still owns a device-resident corpus — it needs the
        # mesh too, or corpus-sharded search silently degrades to one chip.
        # The engine-override guard keeps stub-engine test stacks meshless.
        builds_embedded_store = (
            self._engine_override is None
            and (on("vector_memory") or on("engine"))
            and not cfg.vector_store.uri
            and cfg.vector_store.device_resident)
        if (cfg.parallel.enabled and self._mesh is None
                and (builds_real_engine or builds_real_lm
                     or builds_embedded_store)):
            from symbiont_tpu.parallel.mesh import mesh_from_config

            self._mesh = mesh_from_config(cfg.parallel)
            log.info("serving mesh: %s",
                     dict(self._mesh.shape))
        if self._mesh is not None:
            # mesh.devices{axis}: the serving topology, readable off
            # /metrics (docs/OBSERVABILITY.md)
            for axis, size in dict(self._mesh.shape).items():
                metrics.gauge_set("mesh.devices", size,
                                  labels={"axis": str(axis)})

        # at-least-once pipeline (SURVEY.md §5.3): one durable stream captures
        # the fire-and-forget subjects; each consumer acks after its side
        # effect lands. Request-reply subjects stay core (their failure mode
        # is the caller's timeout + retry). Both the native broker AND the
        # default in-proc bus implement the stream contract now (resilience
        # plane) — bus.durable works on the single-process stack.
        pipeline_stream = None
        if cfg.bus.durable and hasattr(self.bus, "add_stream"):
            pipeline_stream = "pipeline"
            await self.bus.add_stream(
                pipeline_stream,
                [subjects.DATA_RAW_TEXT_DISCOVERED,
                 subjects.DATA_TEXT_WITH_EMBEDDINGS,
                 subjects.DATA_PROCESSED_TEXT_TOKENIZED],
                ack_wait_s=cfg.bus.durable_ack_wait_s,
                max_deliver=cfg.bus.durable_max_deliver)
        elif cfg.bus.durable:
            log.warning("bus.durable requested but transport %s has no "
                        "durable streams (use inproc:// or symbus://)",
                        cfg.bus.url)
        # size the dead-letter quarantine behind GET /api/dlq (inproc bus)
        if hasattr(self.bus, "dlq"):
            self.bus.dlq.capacity = cfg.resilience.dlq_capacity
        if on("preprocessing") or on("engine"):
            self.engine = self._engine_override or TpuEngine(cfg.engine,
                                                             mesh=self._mesh)
        if on("vector_memory") or on("engine"):
            # vector store dim follows the engine's actual hidden size; in a
            # standalone vector_memory worker (no engine in-process) the
            # configured dim must match the remote engine's model
            vs_cfg = cfg.vector_store
            if self.engine and vs_cfg.dim != self.engine.model_cfg.hidden_size:
                import dataclasses

                vs_cfg = dataclasses.replace(
                    vs_cfg, dim=self.engine.model_cfg.hidden_size)
            elif self.engine is None:
                log.warning("vector store dim=%d taken from config "
                            "(no in-process engine to follow)", vs_cfg.dim)
            # uri set (or reference QDRANT_URI alias) → external Qdrant
            # backend; else the embedded TPU-native store
            from symbiont_tpu.memory.qdrant_backend import make_vector_store

            self.vector_store = make_vector_store(
                vs_cfg, mesh=self._mesh, resilience=cfg.resilience)
            if not on("vector_memory"):
                # engine-only deployment: VectorMemoryService isn't there to
                # run the startup ensure, so do it here (idempotent);
                # executor because external backends block on HTTP retries
                await asyncio.get_running_loop().run_in_executor(
                    None, self.vector_store.ensure_collection)
        if on("knowledge_graph") or on("engine"):
            # uri set (or reference NEO4J_URI alias) → external Neo4j backend
            from symbiont_tpu.graph.neo4j_backend import make_graph_store

            self.graph_store = make_graph_store(cfg.graph_store,
                                                resilience=cfg.resilience)
            if not on("knowledge_graph"):
                await asyncio.get_running_loop().run_in_executor(
                    None, self.graph_store.ensure_schema)  # engine-only: see above

        lm_batcher = None
        if cfg.lm.enabled and (on("text_generator") or on("engine")):
            from symbiont_tpu.engine.batcher import GenBatcher
            from symbiont_tpu.engine.lm import LmEngine

            # a mesh with tensor>1 shards the LM megatron-style for TP
            # decode (models larger than one chip); else single-device
            self.lm = LmEngine(cfg.lm, mesh=self._mesh)
            if cfg.gen_journal.enabled:
                # durable generation sessions (docs/RESILIENCE.md): the
                # engine snapshots every stream at its chunk boundaries to
                # <dir>/<role>.genlog; the process supervisor republishes
                # the tails if this process dies mid-stream
                from pathlib import Path

                from symbiont_tpu.resilience.genlog import GenJournal

                jrole = cfg.runner.role or "local"
                self.lm.journal = GenJournal(
                    Path(cfg.gen_journal.dir) / f"{jrole}.genlog",
                    max_bytes=cfg.gen_journal.max_bytes,
                    max_tasks=cfg.gen_journal.max_tasks,
                    fsync=cfg.gen_journal.fsync)
            # one generation micro-batcher shared by the bus surface and the
            # engine plane: concurrent requests decode as one batch. Stored
            # on self BEFORE anything else can raise, so stop() always
            # closes its task.
            lm_batcher = self._lm_batcher = GenBatcher(self.lm)
            await lm_batcher.start()

        # ONE micro-batching queue in front of the device, shared by every
        # in-process caller (preprocessing pipeline + engine.* plane) — two
        # queues would mean concurrent forwards on one engine, the hazard
        # SURVEY.md §5.2 exists to prevent
        batcher = None
        if self.engine is not None:
            from symbiont_tpu.engine.batcher import MicroBatcher

            batcher = MicroBatcher(self.engine)

        if self.engine is not None or self.lm is not None:
            # device-plane memory gauges (bytes in use / peak / limit per
            # local device) — only once jax is demonstrably in play; a
            # CPU-only or api-only process registers nothing
            from symbiont_tpu.obs.device import register_device_gauges
            from symbiont_tpu.obs.hbm import hbm_ledger

            register_device_gauges()
            # the engines/pools/corpus have claimed by now: expose the
            # ledger as hbm.attributed_bytes{subsystem} gauges (+ the
            # per-device residual where the backend reports stats)
            hbm_ledger.register_gauges()

        if on("perception"):
            self.services.append(
                PerceptionService(self.bus, cfg.perception, fetcher=self._fetcher))
        if on("preprocessing"):
            self.services.append(
                PreprocessingService(self.bus, self.engine, batcher=batcher,
                     durable_stream=pipeline_stream))
        if on("vector_memory"):
            self.services.append(VectorMemoryService(
                self.bus, self.vector_store, durable_stream=pipeline_stream,
                coalesce=cfg.vector_store.coalesce,
                coalesce_max_rows=cfg.vector_store.coalesce_max_rows,
                coalesce_max_age_ms=cfg.vector_store.coalesce_max_age_ms))
        if on("knowledge_graph"):
            self.services.append(KnowledgeGraphService(
                self.bus, self.graph_store, durable_stream=pipeline_stream))
        if on("text_generator"):
            # with the LM backend active, skip Markov ingest training — the
            # chain would grow unboundedly while never being used to generate
            lm_stream = (self.lm.generate_stream
                         if self.lm is not None and cfg.lm.stream_chunk > 0
                         else None)
            lm_trainer = None
            if self.lm is not None and cfg.lm.ingest_train:
                from symbiont_tpu.train.online import OnlineLmTrainer

                lm_trainer = OnlineLmTrainer(
                    self.lm, learning_rate=cfg.lm.ingest_train_lr,
                    seq_len=cfg.lm.ingest_train_seq_len,
                    batch_size=cfg.lm.ingest_train_batch,
                    state_path=cfg.lm.train_state_path)
            self.services.append(
                TextGeneratorService(self.bus, lm_batcher=lm_batcher,
                                     lm_stream=lm_stream,
                                     train_on_ingest=lm_batcher is None,
                                     state_path=(cfg.text_generator
                                                 .markov_state_path),
                                     lm_trainer=lm_trainer,
                                     lm_train_min_chars=(
                                         cfg.lm.ingest_train_min_chars),
                                     lm_train_steps=cfg.lm.ingest_train_steps,
                                     # durability plane: the service owns
                                     # mark_done (journal entries survive
                                     # until the result is PUBLISHED) and
                                     # adopts orphaned streams republished
                                     # by the supervisor
                                     journal=(self.lm.journal
                                              if self.lm is not None
                                              else None),
                                     lm_resume=(self.lm.generate_stream
                                                if self.lm is not None
                                                else None),
                                     resume_max_attempts=(
                                         cfg.gen_journal.resume_max_attempts),
                                     resume_backoff_s=(
                                         cfg.gen_journal.resume_backoff_s)))
        if on("engine"):
            from symbiont_tpu.services.engine_service import EngineService

            self.services.append(EngineService(
                self.bus, engine=self.engine, batcher=batcher, lm=self.lm,
                lm_batcher=lm_batcher,
                vector_store=self.vector_store, graph_store=self.graph_store,
                coalesce=cfg.vector_store.coalesce,
                coalesce_max_rows=cfg.vector_store.coalesce_max_rows,
                coalesce_max_age_ms=cfg.vector_store.coalesce_max_age_ms))
        for s in self.services:
            # handler timeout/retry + loop-supervisor knobs (resilience
            # plane); services may further tune their own fields after
            s.apply_resilience(cfg.resilience)
            await s.start()
        if self.api is not None:
            # everything behind the gateway is placed: flip /readyz to 200
            self.api.mark_ready()
            log.info("symbiont stack up: api on %s:%s", cfg.api.host, self.api.port)
        else:
            log.info("symbiont stack up (no api): %s", sorted(want))
        # fleet telemetry plane (obs/fleet.py): active whenever this
        # process runs as a NAMED role in a supervised deployment
        # (runner.role set, or heartbeats on) — a default single-process
        # stack keeps the pre-fleet /metrics byte-identical. The exporter
        # ships this role's metric deltas + finished spans; the API-role
        # process additionally hosts the aggregator that merges every
        # role's telemetry into the federated /metrics, the stitched
        # cross-process traces, and GET /api/fleet.
        fleet_on = (cfg.obs.fleet_export
                    and (bool(cfg.runner.role) or cfg.runner.heartbeat_s > 0))
        if fleet_on:
            from symbiont_tpu.obs.fleet import (
                FleetAggregator,
                TelemetryExporter,
                subscribe_telemetry,
            )

            role = cfg.runner.role or "+".join(sorted(want))
            if self.api is not None:
                self.fleet = FleetAggregator(
                    local_role=role, max_roles=cfg.obs.fleet_roles_max)
                self.fleet.attach(await subscribe_telemetry(self.bus))
                self.api.fleet = self.fleet
            self.fleet_exporter = TelemetryExporter(
                lambda: self.bus, role=role,
                publish_s=cfg.obs.fleet_publish_s,
                spans_max=cfg.obs.fleet_spans_max,
                pending_max=cfg.obs.fleet_pending_max,
                metrics_max=cfg.obs.fleet_metrics_max,
                full_every=cfg.obs.fleet_full_every)
            self.fleet_exporter.start()
        # process-failure plane: liveness heartbeats for the supervisor
        # (resilience/procsup.py), plus the drain subscription the elastic
        # autoscaler's scale-in rides (resilience/autoscale.py). Started
        # LAST — a heartbeat promises the whole stack is placed and
        # consuming, not just that python booted.
        if cfg.runner.heartbeat_s > 0 or cfg.runner.role:
            role = self._hb_role = cfg.runner.role or "+".join(sorted(want))
            self._drain_sub = await self.bus.subscribe(
                f"{subjects.SYS_DRAIN}.{role}")
            self._drain_task = asyncio.create_task(
                self._drain_loop(), name="runner-drain")
        if cfg.runner.heartbeat_s > 0:
            self._heartbeat_task = asyncio.create_task(
                self._heartbeat_loop(self._hb_role, cfg.runner.heartbeat_s),
                name="runner-heartbeat")

    def _heartbeat_payload(self, role: str) -> bytes:
        """One liveness beat. `capacity`/`draining` are the elastic-
        autoscaler fields: capacity 1 means this replica is serving, 0
        means it is draining out and the supervisor should neither route
        hang verdicts at it nor count it as serving headroom. Keys and
        their order are BYTE-PARITY with common.hpp heartbeat_payload
        (cpp-parity lint rule + tests/test_fleet.py pin both)."""
        import json
        import os

        return json.dumps({"role": role, "pid": os.getpid(),
                           "capacity": 0 if self.draining else 1,
                           "draining": self.draining}).encode()

    async def _heartbeat_loop(self, role: str, interval_s: float) -> None:
        from symbiont_tpu.utils.telemetry import metrics

        while True:
            try:
                await self.bus.publish(
                    f"{subjects.SYS_HEARTBEAT}.{role}",
                    self._heartbeat_payload(role))
                metrics.inc("runner.heartbeats", labels={"role": role})
            except ConnectionError:
                # broker gap: the TcpBus send-gate already waited its
                # bounded window; skip this beat and keep beating — the
                # supervisor treats broker-down as "don't judge workers"
                log.debug("heartbeat publish failed (bus disconnected)")
            except RuntimeError:
                return  # bus closed: stack is stopping
            await asyncio.sleep(interval_s)

    async def _drain_loop(self) -> None:
        """Wait for the supervisor's drain request and run the protocol.
        One-shot: the first `_sys.drain.<role>` message retires this
        process."""
        async for _msg in self._drain_sub:
            await self.drain()
            return

    async def drain(self) -> None:
        """The worker half of the drain protocol (scale-in,
        resilience/autoscale.py): stop pulling new durable deliveries
        (consumers detach — unacked work redelivers to surviving queue-
        group members), let in-flight handlers finish, flush the
        UpsertCoalescer (ack-after-flush waits release), finish in-flight
        generation sessions, publish a final heartbeat with
        `draining: true`, and wake main() to exit. Idempotent."""
        from symbiont_tpu.utils.telemetry import metrics

        if self.draining:
            return
        self.draining = True
        metrics.gauge_set("runner.draining", 1)
        log.info("drain requested: detaching consumers and flushing")
        if self.api is not None:
            # a draining gateway goes /readyz 503 first so the LB routes
            # around it before the socket disappears
            self.api.mark_not_ready()
        for s in self.services:
            await s.drain()
        if self._lm_batcher is not None:
            # finishes in-flight generation sessions (close() runs every
            # pending flush to completion before failing the leftovers)
            await self._lm_batcher.close()
        if self._hb_role:
            try:
                # the final beat: tells the supervisor (and /api/fleet)
                # this exit is a DRAIN, not a death
                await self.bus.publish(
                    f"{subjects.SYS_HEARTBEAT}.{self._hb_role}",
                    self._heartbeat_payload(self._hb_role))
            except Exception:
                log.debug("final draining heartbeat failed", exc_info=True)
        log.info("drain complete: exiting")
        self.drained.set()

    async def stop(self) -> None:
        if self._drain_task is not None:
            self._drain_task.cancel()
            try:
                await self._drain_task
            except (asyncio.CancelledError, Exception):
                pass
            self._drain_task = None
        if self._drain_sub is not None:
            self._drain_sub.close()
            self._drain_sub = None
        if self.fleet_exporter is not None:
            await self.fleet_exporter.stop()
            self.fleet_exporter = None
        if self.fleet is not None:
            await self.fleet.detach()
            self.fleet = None
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            try:
                await self._heartbeat_task
            except (asyncio.CancelledError, Exception):
                pass
            self._heartbeat_task = None
        if self.watchdog is not None:
            await self.watchdog.stop()
            self.watchdog = None
        if self.api:
            await self.api.stop()
        for s in self.services:
            await s.stop()
        if self._lm_batcher is not None:
            await self._lm_batcher.close()
        if self.graph_store:
            self.graph_store.close()
        if self.bus and self._bus_override is None:
            await self.bus.close()


async def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    stack = SymbiontStack()
    await stack.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    # exit on an operator signal OR a completed drain (the supervisor's
    # scale-in request — resilience/autoscale.py): a drained worker's last
    # act is a clean rc-0 exit, which the supervisor treats as retirement,
    # not a crash
    waits = [asyncio.ensure_future(stop.wait()),
             asyncio.ensure_future(stack.drained.wait())]
    try:
        await asyncio.wait(waits, return_when=asyncio.FIRST_COMPLETED)
    finally:
        for w in waits:
            w.cancel()
    await stack.stop()


if __name__ == "__main__":
    asyncio.run(main())
