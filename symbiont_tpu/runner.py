"""Single-process runner: bus + engine + all services.

The reference needs docker-compose with 10 containers to run at all
(reference: docker-compose.yml:1-151); this runner hosts the full pipeline in
one process over the in-proc bus (or any subset against the native broker via
config.bus.url). Usage:

    python -m symbiont_tpu.runner            # full stack, config from env
    SYMBIONT_API_PORT=8080 python -m symbiont_tpu.runner
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Optional

from symbiont_tpu.bus import connect
from symbiont_tpu.config import SymbiontConfig, load_config
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.graph.store import GraphStore
from symbiont_tpu.memory.vector_store import VectorStore
from symbiont_tpu.services.api import ApiService
from symbiont_tpu.services.knowledge_graph import KnowledgeGraphService
from symbiont_tpu.services.perception import PerceptionService
from symbiont_tpu.services.preprocessing import PreprocessingService
from symbiont_tpu.services.text_generator import TextGeneratorService
from symbiont_tpu.services.vector_memory import VectorMemoryService

log = logging.getLogger(__name__)


class SymbiontStack:
    """Builds and owns the full service stack; also the e2e-test harness."""

    def __init__(self, config: Optional[SymbiontConfig] = None, bus=None,
                 engine: Optional[TpuEngine] = None, mesh=None,
                 fetcher=None):
        self.config = config or load_config()
        self._bus_override = bus
        self._engine_override = engine
        self._mesh = mesh
        self._fetcher = fetcher
        self.services: list = []
        self.bus = None
        self.engine = None
        self.lm = None
        self.vector_store = None
        self.graph_store = None
        self.api: Optional[ApiService] = None

    async def start(self) -> None:
        cfg = self.config
        self.bus = self._bus_override or await connect(cfg.bus.url)
        self.engine = self._engine_override or TpuEngine(cfg.engine,
                                                         mesh=self._mesh)
        # vector store dim follows the engine's actual hidden size
        vs_cfg = cfg.vector_store
        if vs_cfg.dim != self.engine.model_cfg.hidden_size:
            import dataclasses

            vs_cfg = dataclasses.replace(
                vs_cfg, dim=self.engine.model_cfg.hidden_size)
        self.vector_store = VectorStore(vs_cfg, mesh=self._mesh)
        self.graph_store = GraphStore(cfg.graph_store)

        lm_generate = None
        if cfg.lm.enabled:
            from symbiont_tpu.engine.lm import LmEngine

            self.lm = LmEngine(cfg.lm)
            lm_generate = self.lm.generate

        self.api = ApiService(self.bus, cfg.api, cfg.bus)
        self.services = [
            PerceptionService(self.bus, cfg.perception, fetcher=self._fetcher),
            PreprocessingService(self.bus, self.engine),
            VectorMemoryService(self.bus, self.vector_store),
            KnowledgeGraphService(self.bus, self.graph_store),
            # with the LM backend active, skip Markov ingest training — the
            # chain would grow unboundedly while never being used to generate
            TextGeneratorService(self.bus, lm_generate=lm_generate,
                                 train_on_ingest=lm_generate is None),
        ]
        for s in self.services:
            await s.start()
        await self.api.start()
        log.info("symbiont stack up: api on %s:%s", cfg.api.host, self.api.port)

    async def stop(self) -> None:
        if self.api:
            await self.api.stop()
        for s in self.services:
            await s.stop()
        if self.graph_store:
            self.graph_store.close()
        if self.bus and self._bus_override is None:
            await self.bus.close()


async def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    stack = SymbiontStack()
    await stack.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    await stack.stop()


if __name__ == "__main__":
    asyncio.run(main())
