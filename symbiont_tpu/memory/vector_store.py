"""TPU-native vector store: brute-force exact cosine top-k on the MXU.

Design rationale: at the corpus scales the reference system handles (sentences
of scraped documents), exact search as one [N, D] x [D] matmul on a TPU chip
beats an ANN index round-trip — no gRPC hop, no graph traversal, exact
results, and the matmul rides the MXU at bf16. Rows shard over the mesh 'data'
axis for corpora beyond one chip's HBM (capacity blocks keep shapes static).

API parity with the reference's Qdrant adapter:
- ensure_collection (dim + cosine at startup):
  reference vector_memory_service/src/main.rs:24-119
- upsert(points with uuid ids + QdrantPointPayload-shaped payloads), ack after
  durable: main.rs:121-228 (wait=true at :196)
- search(query, top_k) → hits with id, score, payload: main.rs:230-456

Durability: append-only JSONL WAL + optional compacted .npy snapshot;
load() replays snapshot + WAL tail (SURVEY.md §5.4: DB-as-truth stance kept,
now inside the framework).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from symbiont_tpu.config import VectorStoreConfig

log = logging.getLogger(__name__)


@dataclass
class SearchHit:
    id: str
    score: float
    payload: dict


class VectorStore:
    supports_fused = True  # corpus is device-resident → fused embed+top-k

    def __init__(self, config: Optional[VectorStoreConfig] = None, mesh=None):
        self.config = config or VectorStoreConfig()
        self.mesh = mesh
        self.dim = self.config.dim
        self._lock = threading.RLock()
        self._ids: List[str] = []
        self._id_to_row: Dict[str, int] = {}
        self._payloads: List[dict] = []
        self._vectors = np.zeros((0, self.dim), np.float32)  # L2-normalized rows
        self._device_corpus = None  # padded [capacity_blocks, D] on device
        self._device_rows = 0  # rows valid in the device copy
        self._dirty = True
        self._search_fns: dict = {}
        self._warmed_capacity = None  # capacity warm_fused last compiled for
        self._wal_file = None
        self.last_load_skipped_lines = 0  # corrupt WAL lines on last load()
        # hbm attribution plane (obs/hbm.py): the device-resident corpus
        # claims its padded bytes — .nbytes is host metadata, no sync
        from symbiont_tpu.obs.hbm import hbm_ledger

        hbm_ledger.claim(
            "memory.corpus", self,
            lambda vs: (0 if vs._device_corpus is None
                        else int(vs._device_corpus.nbytes)))
        if self.config.data_dir:
            Path(self.config.data_dir).mkdir(parents=True, exist_ok=True)
            self.load()

    # ------------------------------------------------------------ lifecycle

    def ensure_collection(self, dim: Optional[int] = None) -> None:
        """Validate/establish the collection config (reference: main.rs:24-119).

        Like Qdrant's ensure path this is idempotent; a dim mismatch with
        existing data is an error rather than silent re-create."""
        dim = dim or self.config.dim
        with self._lock:
            if len(self._ids) and dim != self.dim:
                raise ValueError(
                    f"collection '{self.config.collection}' already has dim "
                    f"{self.dim}, requested {dim}")
            self.dim = dim
            if self._vectors.shape[1] != dim:
                self._vectors = np.zeros((0, dim), np.float32)

    def count(self) -> int:
        with self._lock:
            return len(self._ids)

    # -------------------------------------------------------------- upsert

    def upsert(self, points: Sequence[Tuple[str, Sequence[float], dict]]) -> int:
        """Insert or overwrite points; ack only after the WAL write+flush
        (the reference's wait=true durability, main.rs:196). Returns count.

        Normalization is one vectorized pass over the whole batch — the
        per-point numpy calls (asarray + norm per row) were ~1 s of CPU per
        3k-point ingest wave on the one-core host (measured r5)."""
        if not points:
            return 0
        with self._lock:
            try:
                batch = np.asarray([vec for _, vec, _ in points], np.float32)
            except (ValueError, TypeError):
                batch = None  # ragged input: report the offending row below
            if batch is None or batch.ndim != 2 or batch.shape[1] != self.dim:
                for _, vec, _ in points:
                    v = np.asarray(vec, np.float32)
                    if v.shape != (self.dim,):
                        raise ValueError(
                            f"vector dim {v.shape} != collection dim {self.dim}")
                raise ValueError(f"vectors must be [n, {self.dim}]")
            return self._ingest_locked([p[0] for p in points], batch,
                                       [p[2] for p in points])

    def upsert_rows(self, ids: Sequence[str], rows,
                    payloads: Optional[Sequence[dict]] = None) -> int:
        """Tensor-frame fast path: ingest an already-packed [n, dim] float
        block (typically a read-only `np.frombuffer` view straight off the
        bus — schema/frames) without ever materializing per-float Python
        objects. Same semantics and WAL durability as upsert().

        Non-f32 rows (the half-width f16 wire form, or bf16 engine output)
        are upcast to f32 here — the store's in-memory matrix, WAL, and
        search math stay f32 regardless of what dtype rode the bus."""
        ids = list(ids)
        if not ids:
            return 0
        rows = np.asarray(rows, np.float32)  # upcasts f16/f64 views in C
        if rows.ndim != 2 or rows.shape[0] != len(ids):
            raise ValueError(
                f"rows shape {rows.shape} does not match {len(ids)} ids")
        if rows.shape[1] != self.dim:
            raise ValueError(
                f"vector dim ({rows.shape[1]},) != collection dim {self.dim}")
        payloads = ([{}] * len(ids) if payloads is None else list(payloads))
        if len(payloads) != len(ids):
            # zip would silently truncate and drop points
            raise ValueError(f"{len(payloads)} payloads for {len(ids)} ids")
        with self._lock:
            return self._ingest_locked(ids, rows, payloads)

    def _ingest_locked(self, ids: List[str], batch: np.ndarray,
                       payloads: List[dict]) -> int:
        """Shared ingest tail (caller holds the lock, batch is validated
        [n, dim] f32 — possibly a read-only view; the WAL records the RAW
        vectors, normalization happens on the in-memory copy only)."""
        norms = np.linalg.norm(batch, axis=1, keepdims=True)
        normed = np.divide(batch, norms, out=batch.astype(np.float32,
                                                          copy=True),
                           where=norms > 0)
        rows = []
        new_pos: Dict[str, int] = {}  # ids first seen in THIS call — a
        # duplicate id within one batch (e.g. WAL replay of an update)
        # must overwrite, not append twice
        for j, (pid, payload) in enumerate(zip(ids, payloads)):
            if pid in self._id_to_row:
                r = self._id_to_row[pid]
                self._vectors[r] = normed[j]
                self._payloads[r] = dict(payload)
                self._dirty = True
            elif pid in new_pos:
                rows[new_pos[pid]] = (pid, j, dict(payload))
            else:
                new_pos[pid] = len(rows)
                rows.append((pid, j, dict(payload)))
        if rows:
            new_vecs = normed[[j for _, j, _ in rows]]
            base = len(self._ids)
            self._vectors = (np.concatenate([self._vectors, new_vecs])
                             if len(self._vectors) else new_vecs)
            for i, (pid, _, payload) in enumerate(rows):
                self._ids.append(pid)
                self._id_to_row[pid] = base + i
                self._payloads.append(payload)
            self._dirty = True
        self._wal_append(list(zip(ids, batch, payloads)))
        return len(ids)

    # -------------------------------------------------------------- search

    def _capacity(self, n: int) -> int:
        """Static capacity: next multiple of shard_capacity (and of the data
        axis size when sharded) — keeps device shapes stable across growth."""
        block = self.config.shard_capacity
        cap = max(block, ((n + block - 1) // block) * block)
        if self.mesh is not None:
            nd = self.mesh.shape.get("data", 1)
            cap = ((cap + nd - 1) // nd) * nd
        return cap

    def _sync_device(self) -> None:
        import jax
        import jax.numpy as jnp

        n = len(self._ids)
        if self._device_corpus is not None and not self._dirty and self._device_rows == n:
            return
        cap = self._capacity(n)
        padded = np.zeros((cap, self.dim), np.float32)
        if n:
            padded[:n] = self._vectors
        if self.mesh is not None and self.mesh.shape.get("data", 1) > 1:
            from symbiont_tpu.parallel.sharding import batch_sharding

            self._device_corpus = jax.device_put(jnp.asarray(padded),
                                                 batch_sharding(self.mesh))
        else:
            self._device_corpus = jnp.asarray(padded)
        self._device_rows = n
        self._dirty = False

    def _sharded(self, cap: int) -> bool:
        """Corpus rows live sharded over the mesh 'data' axis (capacity is
        rounded to the axis size in _capacity, so this holds whenever a
        multi-device mesh was threaded in)."""
        return (self.mesh is not None
                and self.mesh.shape.get("data", 1) > 1
                and cap % self.mesh.shape["data"] == 0)

    def _get_search_fn(self, cap: int, k: int):
        import jax
        import jax.numpy as jnp

        key = (cap, k)
        if key not in self._search_fns:
            mesh = self.mesh if self._sharded(cap) else None

            def fn(corpus, query, n_valid):
                # cosine == dot product (rows and query pre-normalized);
                # bf16 matmul on the MXU, fp32 scores. Sharded corpora do a
                # per-shard top-k + global merge so only k candidates per
                # shard cross the interconnect — result order identical to
                # the single-device path (parallel/sharding.corpus_topk,
                # pinned in tests/test_multichip_serving.py).
                if mesh is not None:
                    from symbiont_tpu.parallel.sharding import corpus_topk

                    return corpus_topk(mesh, corpus,
                                       query.astype(jnp.bfloat16), n_valid, k)
                q = query.astype(jnp.bfloat16)
                c = corpus.astype(jnp.bfloat16)
                scores = (c @ q).astype(jnp.float32)
                valid = jnp.arange(cap) < n_valid
                scores = jnp.where(valid, scores, -jnp.inf)
                return jax.lax.top_k(scores, k)

            self._search_fns[key] = jax.jit(fn)
        return self._search_fns[key]

    def _k_static(self, top_k: int, n: int, cap: int) -> int:
        """Static k bucket (next power of two ≥ k, ≤ cap) bounds executables.

        Floored at 8 so every interactive query with top_k ≤ 8 (the common
        range) shares ONE executable per (capacity, length-bucket) — without
        the floor, each distinct top_k minted a fresh XLA compile, which on a
        cold engine blows the fused-search probe timeout per k value. Extra
        rows cost nothing (top-8 vs top-2 is the same matmul + tiny sort) and
        surplus entries are trimmed/-inf-filtered by the caller."""
        k = 8
        while k < min(top_k, n):
            k *= 2
        return min(k, cap)

    def _hits_from(self, scores, idx, top_k: int) -> List[SearchHit]:
        hits = []
        for s, i in zip(np.asarray(scores)[:top_k], np.asarray(idx)[:top_k]):
            if not np.isfinite(s):
                continue
            hits.append(SearchHit(id=self._ids[i], score=float(s),
                                  payload=dict(self._payloads[i])))
        return hits

    def search(self, query: Sequence[float], top_k: int) -> List[SearchHit]:
        """Exact cosine top-k (reference search handler: main.rs:230-456).

        The device call (and any first-shape XLA compile, 20-40s on TPU) runs
        OUTSIDE the store lock: rows only ever append (upsert overwrites in
        place), so a snapshot of (corpus, n) taken under the lock stays valid,
        and concurrent ingest/search callers never stall behind a compile."""
        import jax.numpy as jnp

        with self._lock:
            n = len(self._ids)
            if n == 0 or top_k <= 0:
                return []
            self._sync_device()
            corpus = self._device_corpus
            cap = corpus.shape[0]
            q = np.asarray(query, np.float32)
            if q.shape != (self.dim,):
                raise ValueError(f"query dim {q.shape} != collection dim {self.dim}")
            fn = self._get_search_fn(cap, self._k_static(top_k, n, cap))
        qn = float(np.linalg.norm(q))
        q = q / qn if qn > 0 else q
        scores, idx = fn(corpus, jnp.asarray(q), n)
        with self._lock:
            return self._hits_from(scores, idx, top_k)

    def search_fused(self, engine, text: str, top_k: int) -> List[SearchHit]:
        """Interactive-query fast path: hand the device-resident corpus to the
        engine's fused embed+top-k executable (one device round-trip instead
        of embed then search). Same results as search(embed_query(text)) —
        asserted in tests — with the same static-k bucketing."""
        with self._lock:
            n = len(self._ids)
            if n == 0 or top_k <= 0:
                return []
            self._sync_device()
            corpus = self._device_corpus
            k = self._k_static(top_k, n, corpus.shape[0])
        # device call (and any first-shape compile) outside the lock — see
        # search() for why the snapshot stays valid
        scores, idx = engine.embed_and_search(text, corpus, n, k)
        with self._lock:
            return self._hits_from(scores, idx, top_k)

    def warm_fused(self, engine, word_counts: Sequence[int] = (3, 40, 150),
                   top_ks: Optional[Sequence[int]] = None) -> None:
        """Pre-compile the fused embed+top-k executables for the store's
        CURRENT capacity across the engine's query length buckets — including
        an empty store (capacity is the first block, which the first
        shard_capacity upserts keep). Without this, the first fused query per
        (length-bucket, capacity) pays the full XLA compile inside the
        gateway's short probe timeout. Warms every power-of-two k bucket up
        to config.warm_top_k (default 8 and 16) — the gateways route only
        top_k ≤ ApiConfig.fused_search_max_top_k to the fused path, and the
        two knobs must move together — and records the warmed capacity so
        callers can re-warm when upserts cross a capacity block
        (fused_warm_stale)."""
        if top_ks is None:
            top_ks = [8]
            while top_ks[-1] < self.config.warm_top_k:
                top_ks.append(top_ks[-1] * 2)
        with self._lock:
            self._sync_device()
            corpus = self._device_corpus
            n = len(self._ids)
            ks = sorted({self._k_static(k, max(n, k), corpus.shape[0])
                         for k in top_ks})
        for k in ks:
            for wc in word_counts:
                engine.embed_and_search("warm " * wc, corpus, n, k)
        with self._lock:
            self._warmed_capacity = corpus.shape[0]

    def fused_warm_stale(self) -> bool:
        """True when upserts have crossed a capacity block since the last
        warm_fused — the next fused query would pay a fresh XLA compile, so
        the owner should re-run warm_fused in the background."""
        with self._lock:
            return (self._warmed_capacity is not None
                    and self._capacity(len(self._ids)) != self._warmed_capacity)

    # --------------------------------------------------------- persistence

    def _wal_path(self) -> Optional[Path]:
        if not self.config.data_dir:
            return None
        return Path(self.config.data_dir) / f"{self.config.collection}.wal.jsonl"

    def _wal_append(self, points) -> None:
        path = self._wal_path()
        if path is None:
            return
        if self._wal_file is None:
            self._wal_file = open(path, "a", encoding="utf-8")
        # vectors ride as base64 f32 (internal durability format, not wire
        # schema): json-serializing 384 floats per point was the single
        # hottest CPU term of a bulk-ingest wave (measured r5). load()
        # accepts both this and the pre-r5 "vector" float-list records.
        import base64

        lines = []
        for pid, vec, payload in points:
            rec = {"id": pid,
                   "vector_b64": base64.b64encode(
                       np.asarray(vec, np.float32).tobytes()).decode("ascii"),
                   "payload": payload}
            lines.append(json.dumps(rec, ensure_ascii=False))
        self._wal_file.write("\n".join(lines) + "\n")
        self._wal_file.flush()
        os.fsync(self._wal_file.fileno())

    def compact(self) -> None:
        """Snapshot vectors+payloads, truncate the WAL."""
        if not self.config.data_dir:
            return
        with self._lock:
            root = Path(self.config.data_dir)
            np.save(root / f"{self.config.collection}.vectors.npy", self._vectors)
            meta = {"dim": self.dim, "ids": self._ids, "payloads": self._payloads}
            tmp = root / f"{self.config.collection}.meta.json.tmp"
            tmp.write_text(json.dumps(meta, ensure_ascii=False))
            tmp.replace(root / f"{self.config.collection}.meta.json")
            if self._wal_file is not None:
                self._wal_file.close()
                self._wal_file = None
            wal = self._wal_path()
            if wal and wal.exists():
                wal.unlink()

    def load(self) -> None:
        root = Path(self.config.data_dir)
        meta_p = root / f"{self.config.collection}.meta.json"
        with self._lock:
            if meta_p.exists():
                meta = json.loads(meta_p.read_text())
                self.dim = meta["dim"]
                self._ids = list(meta["ids"])
                self._payloads = list(meta["payloads"])
                self._vectors = np.load(root / f"{self.config.collection}.vectors.npy")
                self._id_to_row = {pid: i for i, pid in enumerate(self._ids)}
            wal = self._wal_path()
            skipped = 0
            if wal and wal.exists():
                replay: List[Tuple[str, list, dict]] = []
                with open(wal, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                            if "vector_b64" in rec:
                                import base64

                                vec = np.frombuffer(
                                    base64.b64decode(rec["vector_b64"]),
                                    dtype=np.float32)
                            else:  # pre-r5 float-list records
                                vec = rec["vector"]
                            replay.append((rec["id"], vec, rec["payload"]))
                        except (json.JSONDecodeError, KeyError, ValueError):
                            skipped += 1
                if skipped:
                    # a rollback to a pre-r5 build re-writes this WAL with
                    # float-list records; anything the OLD code cannot parse
                    # (e.g. the r5 vector_b64 format) is not "a corrupt
                    # line", it is DATA LOSS — make the count visible so the
                    # operator knows how many points vanished (compact()
                    # BEFORE rolling back, see docs/DEPLOYMENT.md)
                    log.warning(
                        "%s: skipped %d corrupt/unreadable WAL line(s) — "
                        "these points are NOT loaded; if this follows a "
                        "version rollback, the WAL format changed and the "
                        "skipped records are lost unless re-ingested "
                        "(run compact() before rolling back)",
                        wal, skipped)
                if replay:
                    # replay through upsert minus re-logging
                    wal_file, self._wal_file = self._wal_file, None
                    data_dir, self.config.data_dir = self.config.data_dir, ""
                    try:
                        self.upsert(replay)
                    finally:
                        self.config.data_dir = data_dir
                        self._wal_file = wal_file
            self.last_load_skipped_lines = skipped
            self._dirty = True
