"""Optional external-Qdrant backend for the vector-memory surface.

The framework's default store is the embedded TPU-native one
(memory/vector_store.py — exact cosine on the MXU). Deployments migrating
from the reference, which runs a real Qdrant (reference:
docker-compose.yml:16-25; services/vector_memory_service/src/main.rs), can
keep it: set `vector_store.uri` (or the reference's `QDRANT_URI` env alias)
to the Qdrant HTTP endpoint and the runner swaps this adapter in. Same
collection layout as the reference — named collection, configured dim,
cosine distance (main.rs:20-22,34-42) — so an existing reference Qdrant
volume is readable as-is.

Speaks Qdrant's REST API via stdlib urllib (no qdrant-client dependency):
- PUT  /collections/{name}                 ensure (dim, cosine)
- PUT  /collections/{name}/points?wait=true upsert (the reference's
  wait=true durability stance, main.rs:196)
- POST /collections/{name}/points/search   top-k, payload on, vectors off
  (main.rs:261-286)
- POST /collections/{name}/points/count    exact count

No fused embed+top-k here (the corpus lives in Qdrant, not HBM) —
`supports_fused = False`, so the engine plane serves only the 2-hop path
and the gateway's fused probe falls back exactly as in any non-co-located
deployment.
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request
from typing import List, Optional, Sequence, Tuple

from symbiont_tpu.config import VectorStoreConfig
from symbiont_tpu.memory.vector_store import SearchHit
from symbiont_tpu.utils.retry import connect_retry

log = logging.getLogger(__name__)


class QdrantStore:
    """Vector-memory surface (ensure_collection/upsert/search/count) over a
    remote Qdrant. Connect-retry at startup mirrors the reference's 5×5s
    (reference: vector_memory_service/src/main.rs:505-532)."""

    supports_fused = False

    def __init__(self, config: VectorStoreConfig,
                 retries: int = 5, retry_delay_s: float = 5.0):
        if not config.uri:
            raise ValueError("QdrantStore requires vector_store.uri")
        if not config.uri.startswith(("http://", "https://")):
            raise ValueError(
                f"vector_store.uri must be the Qdrant REST endpoint "
                f"(http://host:6333), got {config.uri!r}")
        self.config = config
        self.base = config.uri.rstrip("/")
        self.collection = config.collection
        self.dim = config.dim
        self._retries = retries
        self._retry_delay_s = retry_delay_s

    # ------------------------------------------------------------------ http

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              timeout: float = 20.0) -> dict:
        req = urllib.request.Request(
            f"{self.base}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"}, method=method)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read() or b"{}")

    # --------------------------------------------------------------- surface

    def ensure_collection(self, dim: Optional[int] = None) -> None:
        if dim is not None:
            self.dim = dim
        body = {"vectors": {"size": self.dim, "distance": "Cosine"},
                "on_disk_payload": True}

        def attempt() -> None:
            try:
                self._call("PUT", f"/collections/{self.collection}", body)
            except urllib.error.HTTPError as e:
                if e.code != 409:  # 409 = already exists
                    raise
                # existing collection: verify its dim matches instead of
                # failing later on every upsert (the embedded store's
                # fail-fast stance)
                info = self._call("GET", f"/collections/{self.collection}")
                have = (info.get("result", {}).get("config", {})
                        .get("params", {}).get("vectors", {}).get("size"))
                if have is not None and int(have) != self.dim:
                    raise ValueError(
                        f"collection {self.collection!r} exists with "
                        f"dim={have}, engine produces dim={self.dim}")
            log.info("qdrant collection %r ready (dim=%d, cosine)",
                     self.collection, self.dim)

        # dim mismatch is a config error, not a connectivity one — no retry
        connect_retry(attempt, retries=self._retries,
                      delay_s=self._retry_delay_s,
                      what=f"qdrant at {self.base}", fatal=(ValueError,))

    # Real Qdrant caps the JSON request body (32MB default); a 768-dim f32
    # point is ~10KB as JSON text, so bulk upserts must chunk. 512 points ≈
    # 5MB per request — safely under the cap with headroom for payloads.
    UPSERT_CHUNK = 512

    def upsert(self, points: Sequence[Tuple[str, Sequence[float], dict]]) -> int:
        """Chunked wait=true upsert. NOT atomic across chunks: a hard failure
        on chunk i>0 raises after earlier chunks committed (the raised
        HTTPError/URLError carries `.points_committed` with how many points
        landed). Safe to retry the WHOLE call: point ids are deterministic,
        so re-upserting committed chunks is idempotent overwriting."""
        if not points:
            return 0
        for i in range(0, len(points), self.UPSERT_CHUNK):
            chunk = points[i:i + self.UPSERT_CHUNK]
            body = {"points": [{"id": pid, "vector": [float(x) for x in vec],
                                "payload": payload}
                               for pid, vec, payload in chunk]}
            try:
                self._call("PUT",
                           f"/collections/{self.collection}/points?wait=true",
                           body)
            except Exception as e:
                e.points_committed = i  # partial-commit marker for callers
                raise
        return len(points)

    def search(self, query: Sequence[float], top_k: int) -> List[SearchHit]:
        if top_k <= 0:
            return []
        body = {"vector": [float(x) for x in query], "limit": int(top_k),
                "with_payload": True, "with_vector": False}
        out = self._call("POST",
                         f"/collections/{self.collection}/points/search", body)
        return [SearchHit(id=str(h["id"]), score=float(h["score"]),
                          payload=h.get("payload") or {})
                for h in out.get("result", [])]

    def count(self) -> int:
        out = self._call("POST",
                         f"/collections/{self.collection}/points/count",
                         {"exact": True})
        return int(out.get("result", {}).get("count", 0))


def make_vector_store(config: VectorStoreConfig, mesh=None, resilience=None):
    """Backend selection: uri set → external Qdrant; else the embedded
    TPU-native store (the default and the fast path).

    With a ResilienceConfig (and breakers enabled), the EXTERNAL backend is
    wrapped in a circuit breaker + WAL spill (resilience/stores.py): a
    mid-run Qdrant outage degrades to local spooling instead of turning
    every embedding into a dropped write. The embedded store needs no
    breaker — its failure domain is the process itself."""
    if config.uri:
        store = QdrantStore(config)
        if resilience is not None and resilience.breaker_enabled:
            from pathlib import Path

            from symbiont_tpu.resilience.breaker import CircuitBreaker
            from symbiont_tpu.resilience.stores import ResilientVectorStore

            return ResilientVectorStore(
                store,
                breaker=CircuitBreaker(
                    "vector_store",
                    failure_threshold=resilience.breaker_failure_threshold,
                    reset_timeout_s=resilience.breaker_reset_timeout_s),
                spill_path=str(Path(resilience.spill_dir)
                               / f"{config.collection}.spill.jsonl"))
        return store
    from symbiont_tpu.memory.vector_store import VectorStore

    return VectorStore(config, mesh=mesh)
