"""Vector memory — the Qdrant-parity store, TPU-native.

The reference delegates similarity search to an external Qdrant server over
gRPC (reference: services/vector_memory_service/src/main.rs:24-119 ensure,
:121-228 upsert, :230-456 search). Here the store is part of the framework:
vectors live in a device-resident matrix and search is an MXU matmul + top-k
(symbiont_tpu/memory/vector_store.py), sharded over the mesh for large
corpora. Durability is write-ahead-logged on the host (upsert acks after the
WAL fsync — the reference's wait=true stance, main.rs:196).
"""

from symbiont_tpu.memory.vector_store import SearchHit, VectorStore

__all__ = ["VectorStore", "SearchHit"]
