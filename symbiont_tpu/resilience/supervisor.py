"""Restart-with-backoff supervision for long-lived service loop tasks.

The pre-resilience `Service._subscribe_loop` spawned its dispatch loop with
a bare `asyncio.create_task` and never looked at it again: an exception in
the loop body killed the consumer silently — the service kept reporting
healthy while eating no messages (the exact failure shape SURVEY.md §5.3
documents for the reference's spawned handlers). `supervise()` wraps such a
loop: a crash is logged with traceback, counted
(`service.loop_restarts{task=...}`), and the loop restarts after a jittered
exponential backoff. A clean return (subscription closed) or cancellation
(service stop) ends supervision.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Awaitable, Callable, Dict, Optional

from symbiont_tpu.utils.retry import jittered
from symbiont_tpu.utils.telemetry import metrics

log = logging.getLogger(__name__)

__all__ = ["supervise", "jittered"]


async def supervise(factory: Callable[[], Awaitable[None]], *, name: str,
                    backoff_base_s: float = 0.5, backoff_max_s: float = 30.0,
                    labels: Optional[Dict[str, str]] = None,
                    still_wanted: Callable[[], bool] = lambda: True,
                    rng: Optional[random.Random] = None) -> None:
    """Run `await factory()` until it returns cleanly, restarting on
    exceptions with exponential backoff. `still_wanted` is consulted before
    each restart so a stopping service doesn't resurrect its loops."""
    delay = backoff_base_s
    while True:
        try:
            await factory()
            return  # clean exit: subscription closed / service stopping
        except asyncio.CancelledError:
            raise
        except Exception:
            if not still_wanted():
                return
            metrics.inc("service.loop_restarts",
                        labels={**(labels or {}), "task": name})
            log.exception("supervised task %r crashed; restarting in %.2fs",
                          name, delay)
            await asyncio.sleep(jittered(delay, rng))
            delay = min(delay * 2, backoff_max_s)
            if not still_wanted():
                return
