"""Overload-protection plane: admission control, deadlines, SLO shedding.

The resilience plane (streams/DLQ/breakers) makes the pipeline survive
FAULTS; this module makes it survive LOAD. Four cooperating pieces, all
wired at the API edge (services/api.py) and the service base
(services/base.py), proven under chaos by bench/load.py:

- `TokenBucket` + `AdmissionController` — per-tenant quotas per request
  class (ingest / search / generate). Tenant identity comes from the
  `X-Symbiont-Tenant` HTTP header (default tenant otherwise); an exhausted
  bucket is answered 429-with-Retry-After at the edge instead of queuing
  unboundedly. One hot tenant is clamped to its quota; everyone else keeps
  theirs.
- `WeightedFairQueue` — bounded per-tenant wait queues over a shared
  concurrency budget (stride scheduling by configured weights): when the
  search path saturates, slots hand out fairly across tenants instead of
  FIFO across the hot tenant's backlog; a full tenant queue rejects (429),
  never grows.
- deadline helpers — an `X-Symbiont-Deadline` header (absolute unix epoch
  ms) minted at the edge and threaded through every bus hop by
  `telemetry.child_headers`; `expired()` lets `Service._run_handler` drop
  dead work BEFORE the handler runs: counted as `admission.expired{service}`,
  ACKED on durable streams (never retried, never quarantined as poison —
  expiry is the caller giving up, not the handler failing).
- `DegradationLadder` — SLO-aware shedding driven by SloWatchdog breach
  passes (obs/watchdog.py listeners), with breaker-style hysteresis (dwell
  time both directions + N consecutive healthy passes to step down, so an
  oscillating breach cannot flap the level). Rungs: shed lowest-priority
  generation first, then degrade search (clamped top-k, rerank skipped).
  Ingest acks are NEVER shed — losing accepted data is worse than slow data.

Everything takes an injectable clock so tests assert refill/hysteresis
timing exactly; nothing here imports jax or any service module.
"""

from __future__ import annotations

import asyncio
import math
import time
from collections import deque
from typing import Callable, Dict, Optional

from symbiont_tpu.utils.telemetry import (
    DEADLINE_HEADER,
    TENANT_HEADER,
    metrics,
)

DEFAULT_TENANT = "default"

# the shared identity every tenant beyond AdmissionConfig.max_tenants maps
# to: the X-Symbiont-Tenant header is CLIENT-supplied, so without a bound an
# attacker minting a fresh tenant per request would get a fresh full-burst
# bucket every time (quota bypass) while growing buckets / fair-queue state /
# metric label cardinality without limit — the exact unbounded-growth-under-
# overload this plane exists to prevent
OVERFLOW_TENANT = "(overflow)"

# request classes the controller quotas independently
CLASSES = ("ingest", "search", "generate")

# generation priorities (X-Symbiont-Priority); unknown values → "normal"
PRIORITIES = ("low", "normal", "high")


class AdmissionReject(Exception):
    """Raised when a request must be answered 429: quota exhausted, fair
    queue full, or capacity/shed refusal. Carries the Retry-After hint and
    a bounded-cardinality reason label for `admission.*` counters."""

    def __init__(self, reason: str, retry_after_s: float = 1.0,
                 message: str = ""):
        super().__init__(message or reason)
        self.reason = reason
        self.retry_after_s = max(0.0, float(retry_after_s))


# ------------------------------------------------------------ token buckets


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill up to `burst`. Injectable
    clock; no background task — tokens materialize lazily at take time."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0 or burst <= 0:
            raise ValueError("token bucket rate and burst must be positive")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will have refilled (the Retry-After
        hint a 429 carries)."""
        self._refill()
        deficit = n - self._tokens
        return max(0.0, deficit / self.rate)


# ------------------------------------------------------- stride scheduling


class StrideClock:
    """The stride-scheduling core shared by the edge fair queue and the
    engine batcher's tenant lanes (PR 10): each grant charges the tenant's
    virtual time by 1/weight, and the pending tenant with the SMALLEST
    effective virtual time goes next. The global clock (`vnow`) follows
    EVERY grant so a tenant active while uncontended banks no virtual
    lateness, and a tenant returning from idle starts at the current floor
    instead of its stale past time (no burst catch-up) — both behaviors
    are regression-pinned by tests/test_admission.py."""

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0):
        self.weights = dict(weights or {})
        self.default_weight = float(default_weight)
        self._vtime: Dict[str, float] = {}
        self._vnow = 0.0  # floor for tenants returning from idle

    def _weight(self, tenant: str) -> float:
        return max(1e-6, float(self.weights.get(tenant,
                                                self.default_weight)))

    def effective(self, tenant: str) -> float:
        """The virtual time a grant to `tenant` would happen at."""
        return max(self._vtime.get(tenant, 0.0), self._vnow)

    def pick(self, tenants) -> Optional[str]:
        """The pending tenant that goes next (smallest effective virtual
        time; name breaks exact ties deterministically). None when empty."""
        best = None
        for t in tenants:
            key = (self.effective(t), t)
            if best is None or key < best:
                best = key
        return None if best is None else best[1]

    def charge(self, tenant: str) -> None:
        """Record one grant: advance the global clock to the grant's
        virtual time and push the tenant's next entitlement out by
        1/weight."""
        v = self.effective(tenant)
        self._vnow = v
        self._vtime[tenant] = v + 1.0 / self._weight(tenant)

    def forget(self, tenant: str) -> None:
        """Drop a drained tenant's bookkeeping once it carries at most ONE
        grant of debt — after a tenant's last grant its vtime sits exactly
        1/weight past the floor, so an at-the-floor-only condition would
        never fire and the dict would grow with every identity ever seen.
        Erasing ≤ one grant of lateness is the same forgiveness the
        idle-return floor already grants (effective() clamps to vnow)."""
        if (self._vtime.get(tenant, 0.0)
                <= self._vnow + 1.0 / self._weight(tenant)):
            self._vtime.pop(tenant, None)

    def snapshot(self) -> "StrideClock":
        """Cheap copy for non-mutating fair-order walks."""
        c = StrideClock(self.weights, self.default_weight)
        c._vtime = dict(self._vtime)
        c._vnow = self._vnow
        return c


# ------------------------------------------------------- weighted-fair queue


class WeightedFairQueue:
    """Bounded per-tenant wait queues over a shared concurrency budget.

    Stride scheduling: each grant charges the tenant's virtual time by
    1/weight; the pending tenant with the SMALLEST virtual time is served
    next, so a tenant with weight 4 gets 4 slots for every 1 a weight-1
    tenant gets — and a hot tenant's deep backlog can never starve a light
    tenant (the light tenant's next request always has an earlier virtual
    time than the hot tenant's Nth). A tenant whose queue is full rejects
    immediately (`AdmissionReject("queue_full")`) — bounded memory, shed
    instead of unbounded growth.

    Event-loop-only state (no locks): acquire/release run on the loop.
    """

    def __init__(self, concurrency: int = 32, max_queue: int = 64,
                 weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0):
        if concurrency < 1 or max_queue < 1:
            raise ValueError("concurrency and max_queue must be >= 1")
        self.concurrency = concurrency
        self.max_queue = max_queue
        # the stride core is shared with the engine batcher's tenant lanes
        # (engine/batcher.TenantLanes) — one scheduling policy, two planes
        self._clock = StrideClock(weights, default_weight)
        self._free = concurrency
        self._waiting: Dict[str, deque] = {}

    @property
    def weights(self) -> Dict[str, float]:
        return self._clock.weights

    def queued(self, tenant: Optional[str] = None) -> int:
        if tenant is not None:
            return len(self._waiting.get(tenant, ()))
        return sum(len(q) for q in self._waiting.values())

    async def acquire(self, tenant: str) -> None:
        if self._free > 0 and not self._waiting:
            self._free -= 1
            self._charge(tenant)
            return
        q = self._waiting.setdefault(tenant, deque())
        if len(q) >= self.max_queue:
            metrics.inc("admission.queue_rejected",
                        labels={"tenant": tenant})
            raise AdmissionReject(
                "queue_full", retry_after_s=1.0,
                message=f"tenant {tenant!r} fair-queue is full "
                        f"({self.max_queue} waiting)")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        q.append(fut)
        metrics.gauge_set("admission.queued", self.queued())
        try:
            await fut
        except asyncio.CancelledError:
            # caller gave up while queued: withdraw, or hand the slot back
            # if the grant raced the cancellation
            if fut in q:
                q.remove(fut)
                if not q:
                    # an empty deque left mapped would park the uncontended
                    # fast path forever (acquire checks `not self._waiting`)
                    # with no slot holder left to ever run _grant
                    del self._waiting[tenant]
            elif fut.cancelled() is False and fut.done():
                self.release(tenant)
            raise
        finally:
            metrics.gauge_set("admission.queued", self.queued())

    def _charge(self, tenant: str) -> None:
        # returning-from-idle tenants start at the current floor (no burst
        # catch-up), and the global clock follows EVERY grant, fast-path
        # ones included: a tenant active while the queue was empty must not
        # bank virtual lateness that lets later contenders monopolize the
        # slots (and starve it into queue_full 429s) until they catch up —
        # both behaviors live in StrideClock.charge now
        self._clock.charge(tenant)

    def release(self, tenant: str) -> None:
        self._free += 1
        self._grant()

    def _grant(self) -> None:
        while self._free > 0:
            tenant = self._clock.pick(
                t for t, q in self._waiting.items() if q)
            if tenant is None:
                return
            q = self._waiting[tenant]
            fut = q.popleft()
            if not q:
                del self._waiting[tenant]
            if fut.done():  # cancelled while queued
                continue
            self._free -= 1
            self._charge(tenant)
            fut.set_result(None)


# -------------------------------------------------------------- controller


class AdmissionController:
    """Per-tenant token-bucket quotas per request class + the shared
    weighted-fair queue for the search concurrency budget. Built from
    `AdmissionConfig` (config.py) by the runner; owned by the API service.

    Buckets are created lazily per (tenant, class) — tenant cardinality is
    whatever the deployment sends, so the label space is operator-bounded,
    not framework-bounded."""

    def __init__(self, cfg=None, clock: Callable[[], float] = time.monotonic):
        from symbiont_tpu.config import AdmissionConfig

        self.cfg = cfg or AdmissionConfig()
        self._clock = clock
        self._buckets: Dict[tuple, TokenBucket] = {}
        self.fair_queue = WeightedFairQueue(
            concurrency=self.cfg.search_concurrency,
            max_queue=self.cfg.max_queue_per_tenant,
            weights=parse_weights(self.cfg.fair_weights))
        # distinct tenant identities this controller will track (see
        # resolve_tenant / OVERFLOW_TENANT)
        self._seen_tenants: set = {DEFAULT_TENANT}

    def resolve_tenant(self, tenant: str) -> str:
        """Bound the tenant universe: known tenants (seen before, or named
        in fair_weights — i.e. operator-configured) resolve to themselves;
        once max_tenants distinct identities exist, every NEW one shares
        the overflow identity, its single set of buckets, and its one fair
        queue — so minting fresh tenant headers stops buying fresh burst
        budgets and stops growing state."""
        if (tenant in self._seen_tenants
                or tenant in self.fair_queue.weights):
            return tenant
        if len(self._seen_tenants) >= self.cfg.max_tenants:
            metrics.inc("admission.tenant_overflow")
            return OVERFLOW_TENANT
        self._seen_tenants.add(tenant)
        return tenant

    def _bucket(self, tenant: str, klass: str) -> TokenBucket:
        key = (tenant, klass)
        b = self._buckets.get(key)
        if b is None:
            rate = getattr(self.cfg, f"{klass}_rate")
            burst = getattr(self.cfg, f"{klass}_burst")
            b = self._buckets[key] = TokenBucket(rate, burst,
                                                clock=self._clock)
        return b

    def admit(self, klass: str, tenant: str) -> None:
        """One admission decision at the edge. Raises AdmissionReject
        (→ 429 + Retry-After) on quota exhaustion; counts both outcomes."""
        if klass not in CLASSES:
            raise ValueError(f"unknown admission class {klass!r}")
        bucket = self._bucket(tenant, klass)
        if bucket.try_take():
            metrics.inc("admission.admitted",
                        labels={"class": klass, "tenant": tenant})
            return
        metrics.inc("admission.throttled",
                    labels={"class": klass, "tenant": tenant})
        raise AdmissionReject(
            "quota", retry_after_s=bucket.retry_after_s(),
            message=f"tenant {tenant!r} over its {klass} quota")


def parse_weights(spec: str) -> Dict[str, float]:
    """`"gold=4,free=1"` → {"gold": 4.0, "free": 1.0}. Raises ValueError on
    malformed entries — a typo'd weight must fail at boot, not silently
    weight 1."""
    out: Dict[str, float] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, raw = entry.partition("=")
        if not sep or not name.strip():
            raise ValueError(
                f"fair weight {entry!r} must look like 'tenant=weight'")
        try:
            w = float(raw)
        except ValueError:
            raise ValueError(
                f"fair weight {entry!r}: {raw!r} is not a number") from None
        if w <= 0:
            raise ValueError(f"fair weight {entry!r} must be positive")
        out[name.strip()] = w
    return out


# ---------------------------------------------------------------- deadlines


def mint_deadline(budget_ms: float, headers: Optional[dict] = None,
                  clock: Callable[[], float] = time.time) -> Optional[str]:
    """The edge's deadline header value: now + budget, tightened by any
    client-supplied deadline (a client promising less time wins; a client
    promising MORE cannot extend the operator's budget). budget <= 0
    disables minting (a client deadline still passes through)."""
    client = parse_deadline_ms(headers)
    if budget_ms <= 0:
        return None if client is None else str(int(client))
    minted = clock() * 1000.0 + budget_ms
    if client is not None:
        minted = min(minted, client)
    return str(int(minted))


def parse_deadline_ms(headers: Optional[dict]) -> Optional[float]:
    """The absolute epoch-ms deadline out of a (bus or lowercased HTTP)
    header dict; None when absent or unparseable (garbage must not make
    work immortal OR instantly dead — it is simply no deadline)."""
    if not headers:
        return None
    raw = headers.get(DEADLINE_HEADER) or headers.get(DEADLINE_HEADER.lower())
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


def expired(headers: Optional[dict],
            clock: Callable[[], float] = time.time) -> bool:
    dl = parse_deadline_ms(headers)
    return dl is not None and clock() * 1000.0 > dl


def remaining_ms(headers: Optional[dict],
                 clock: Callable[[], float] = time.time) -> Optional[float]:
    dl = parse_deadline_ms(headers)
    return None if dl is None else dl - clock() * 1000.0


def tenant_of(headers: Optional[dict]) -> str:
    """Tenant identity from a (bus or lowercased HTTP) header dict."""
    if not headers:
        return DEFAULT_TENANT
    raw = (headers.get(TENANT_HEADER)
           or headers.get(TENANT_HEADER.lower()) or "")
    raw = raw.strip()
    return raw or DEFAULT_TENANT


def retry_after_header(seconds: float) -> Dict[str, str]:
    """RFC-shaped Retry-After (integer seconds, rounded up, minimum 1)."""
    return {"Retry-After": str(max(1, math.ceil(seconds)))}


# --------------------------------------------------------- shedding ladder


class DegradationLadder:
    """SLO-aware shedding with breaker-style hysteresis.

    Driven by SloWatchdog evaluation passes (`watchdog.add_listener(
    ladder.on_slo_pass)`): a pass with ≥1 breach escalates one rung (at
    most once per `hold_s` dwell window); stepping DOWN needs
    `recovery_passes` consecutive breach-free passes AND the dwell time —
    so an oscillating breach (breach, clear, breach, ...) parks the ladder
    at its current rung instead of flapping.

    Rungs (never touching ingest — accepted data is never shed):
      0  normal
      1  shed lowest-priority generation (`X-Symbiont-Priority: low`)
      2  shed all non-high generation AND degrade search: top-k clamped to
         `degraded_top_k`, cross-encoder rerank skipped
    """

    MAX_LEVEL = 2
    RUNGS = ("normal", "shed_gen_low", "degrade_search")

    def __init__(self, recovery_passes: int = 3, hold_s: float = 5.0,
                 degraded_top_k: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        if recovery_passes < 1:
            raise ValueError("recovery_passes must be >= 1")
        self.recovery_passes = recovery_passes
        self.hold_s = float(hold_s)
        self.degraded_top_k = int(degraded_top_k)
        self._clock = clock
        self.level = 0
        self._healthy = 0
        self._last_change = clock() - self.hold_s  # first breach acts now
        metrics.gauge_set("admission.level", 0)

    def on_slo_pass(self, breaches) -> None:
        self.observe(bool(breaches))

    def observe(self, breached: bool) -> None:
        """One watchdog evaluation outcome. Idempotent per pass."""
        now = self._clock()
        if breached:
            self._healthy = 0
            if (self.level < self.MAX_LEVEL
                    and now - self._last_change >= self.hold_s):
                self.level += 1
                self._last_change = now
                metrics.inc("admission.level_changes",
                            labels={"direction": "up"})
        else:
            self._healthy += 1
            if (self.level > 0 and self._healthy >= self.recovery_passes
                    and now - self._last_change >= self.hold_s):
                self.level -= 1
                self._last_change = now
                self._healthy = 0
                metrics.inc("admission.level_changes",
                            labels={"direction": "down"})
        metrics.gauge_set("admission.level", self.level)

    # ------------------------------------------------------------- queries

    def shed_generation(self, priority: str = "normal") -> Optional[str]:
        """The shed reason when a generation request must be refused at the
        current rung, else None. high priority is only ever shed by quota /
        capacity, never by the ladder."""
        if priority not in PRIORITIES:
            priority = "normal"
        if self.level >= 2 and priority != "high":
            return self.RUNGS[2]
        if self.level >= 1 and priority == "low":
            return self.RUNGS[1]
        return None

    def search_degraded(self) -> bool:
        return self.level >= 2

    def degrade_top_k(self, top_k: int) -> int:
        return min(int(top_k), self.degraded_top_k) \
            if self.search_degraded() else int(top_k)
