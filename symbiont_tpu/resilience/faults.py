"""Deterministic fault-injection harness for the chaos suite.

A `FaultPlan` is a seeded list of `FaultRule`s consulted at fixed seams in
the framework (bus publish/deliver, handler invocation, store calls, TCP
sends). Production cost is one module-attribute read per seam: with no plan
active every seam check is `if _ACTIVE is None: return None`.

Determinism contract: given the same seed, the same rules, and the same
sequence of seam operations, a plan fires the same faults at the same
operations — chaos tests assert exact loss/recovery counts, so nothing here
reads the wall clock or an unseeded RNG.

Seams (the `seam` a rule names → where it is consulted):
- "bus.publish"   InprocBus.publish (kinds: drop, delay, error)
- "bus.deliver"   inproc durable pump, per delivery attempt (drop, delay)
- "handler"       Service._run_handler, inside the timeout window
                  (error, hang, delay); key is "<service>:<subject>"
- "store.upsert"  ResilientVectorStore.upsert (error, reset)
- "store.search"  ResilientVectorStore.search (error, reset)
- "graph.save"    ResilientGraphStore.save_tokenized (error, reset)
- "tcp.send"      TcpBus._send_frame (reset)
"""

from __future__ import annotations

import asyncio
import fnmatch
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class FaultInjected(RuntimeError):
    """The exception raised by kind="error" rules (and the marker chaos
    tests catch to tell injected failures from real bugs)."""


@dataclass
class FaultRule:
    """One injectable fault. Matching is positional and deterministic:
    each rule keeps its own count of matching operations; it fires on
    operations `after <= i < after + times` (by that count), gated by
    `prob` drawn from the plan's seeded RNG."""

    seam: str
    kind: str  # "error" | "drop" | "delay" | "hang" | "reset"
    match: str = "*"  # fnmatch pattern over the seam's op key
    times: int = 1  # max fires; 0 = unlimited
    after: int = 0  # skip the first `after` matching operations
    delay_s: float = 0.0  # for delay/hang kinds
    prob: float = 1.0  # fire probability per eligible operation
    message: str = ""  # error text override

    _KINDS = ("error", "drop", "delay", "hang", "reset")

    def __post_init__(self) -> None:
        if self.kind not in self._KINDS:
            raise ValueError(f"fault kind must be one of {self._KINDS}, "
                             f"got {self.kind!r}")


@dataclass
class _RuleState:
    matched: int = 0
    fired: int = 0


class FaultPlan:
    """Seeded, inspectable fault schedule. Use as:

        plan = FaultPlan(seed=7, rules=[
            FaultRule(seam="handler", kind="error",
                      match="vector_memory:*", times=2)])
        with plan.activate():
            ... run the stack ...
        assert plan.fired[("handler", "error")] == 2
    """

    def __init__(self, seed: int = 0, rules: Optional[List[FaultRule]] = None):
        self.seed = seed
        self.rules = list(rules or [])
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self._state: Dict[int, _RuleState] = {
            i: _RuleState() for i in range(len(self.rules))}
        # (seam, kind) -> fire count; test introspection surface
        self.fired: Dict[Tuple[str, str], int] = {}
        # every fired (seam, kind, key) in order; deterministic transcript
        self.log: List[Tuple[str, str, str]] = []

    def add(self, rule: FaultRule) -> "FaultPlan":
        with self._lock:
            self._state[len(self.rules)] = _RuleState()
            self.rules.append(rule)
        return self

    # ------------------------------------------------------------- matching

    def check(self, seam: str, key: str) -> Optional[FaultRule]:
        """Return the rule firing for this operation, or None. Counts the
        operation against every rule of the seam whose pattern matches
        (each rule sees its own op index), first firing rule wins."""
        with self._lock:
            hit: Optional[FaultRule] = None
            for i, rule in enumerate(self.rules):
                if rule.seam != seam or not fnmatch.fnmatch(key, rule.match):
                    continue
                st = self._state[i]
                idx = st.matched
                st.matched += 1
                if hit is not None:
                    continue  # already firing this op; keep counting others
                if idx < rule.after:
                    continue
                if rule.times and st.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                    continue
                st.fired += 1
                k = (seam, rule.kind)
                self.fired[k] = self.fired.get(k, 0) + 1
                self.log.append((seam, rule.kind, key))
                hit = rule
            return hit

    # ------------------------------------------------------------- applying

    def _raise(self, rule: FaultRule, seam: str, key: str) -> None:
        msg = rule.message or f"injected {rule.kind} at {seam} ({key})"
        if rule.kind == "reset":
            raise ConnectionResetError(msg)
        raise FaultInjected(msg)

    def sync_fault(self, seam: str, key: str) -> Optional[FaultRule]:
        """Blocking-context seam (store calls run in executor threads).
        Raises for error/reset, sleeps for delay/hang, returns the rule for
        drop (caller decides what dropping means at its seam)."""
        rule = self.check(seam, key)
        if rule is None:
            return None
        if rule.kind in ("delay", "hang"):
            time.sleep(rule.delay_s)
            return rule
        if rule.kind == "drop":
            return rule
        self._raise(rule, seam, key)
        return rule  # unreachable

    async def async_fault(self, seam: str, key: str) -> Optional[FaultRule]:
        """Event-loop seam. Same contract as sync_fault with awaitable
        sleeps — a "hang" inside a handler is an `await asyncio.sleep`
        the handler-timeout cancellation can actually cancel."""
        rule = self.check(seam, key)
        if rule is None:
            return None
        if rule.kind in ("delay", "hang"):
            await asyncio.sleep(rule.delay_s)
            return rule
        if rule.kind == "drop":
            return rule
        self._raise(rule, seam, key)
        return rule  # unreachable

    # ------------------------------------------------------------ lifecycle

    @contextmanager
    def activate(self):
        """Install this plan as the process-active one for the duration.
        Nestable (the previous plan is restored); chaos tests wrap each
        scenario so no fault leaks across tests."""
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev


_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    """The seams' entry point — None (the fast path) unless a chaos test
    has a plan activated."""
    return _ACTIVE
