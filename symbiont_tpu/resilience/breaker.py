"""Circuit breaker for the external store backends.

`utils/retry.py` covers startup connects; this covers the mid-run outage a
heavy-traffic deployment guarantees (ROADMAP north-star): after
`failure_threshold` consecutive failures the breaker OPENS and callers fail
fast (or degrade — see resilience/stores.py for the WAL-spill policy)
instead of stacking `retries x delay` blocking waits in the executor pool.
After `reset_timeout_s` one probe call is let through (HALF-OPEN); success
closes the breaker, failure re-opens it for another window.

State is exported as gauges so the PR-2 observability plane can prove the
degradation story: `breaker.state{name=...}` (0 closed / 1 half-open /
2 open), plus `breaker.opened`/`breaker.failures`/`breaker.fast_fail`
counters. Thread-safe: store calls run in executor threads.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple, Type

from symbiont_tpu.utils.telemetry import metrics

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitOpenError(ConnectionError):
    """Raised (fast, no network wait) when a call is refused by an open
    breaker. Subclasses ConnectionError so existing except-clauses around
    store calls treat it like the outage it represents."""

    def __init__(self, name: str, retry_in_s: float):
        super().__init__(
            f"circuit breaker {name!r} is open (probe in {retry_in_s:.1f}s)")
        self.breaker_name = name
        self.retry_in_s = retry_in_s


class CircuitBreaker:
    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_timeout_s <= 0:
            raise ValueError("reset_timeout_s must be positive")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock  # injectable for deterministic tests
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._export()

    # ------------------------------------------------------------ state api

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _export(self) -> None:
        metrics.gauge_set("breaker.state", _STATE_GAUGE[self._state],
                          labels={"name": self.name})

    def _maybe_half_open(self) -> None:
        # caller holds the lock
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            self._state = HALF_OPEN
            self._probe_inflight = False
            self._export()

    def allow(self) -> bool:
        """True if a call may proceed. In HALF-OPEN exactly one in-flight
        probe is admitted; everyone else keeps failing fast until the probe
        settles."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._state = CLOSED
                metrics.inc("breaker.closed", labels={"name": self.name})
            self._export()

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            metrics.inc("breaker.failures", labels={"name": self.name})
            self._probe_inflight = False
            if self._state == HALF_OPEN or (
                    self._state == CLOSED
                    and self._failures >= self.failure_threshold):
                self._state = OPEN
                self._opened_at = self._clock()
                metrics.inc("breaker.opened", labels={"name": self.name})
            self._export()

    def retry_in_s(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self.reset_timeout_s
                       - (self._clock() - self._opened_at))

    # ------------------------------------------------------------- wrapping

    def call(self, fn: Callable, *args,
             fatal: Tuple[Type[BaseException], ...] = (), **kwargs):
        """Run fn through the breaker: refuse fast when open, record the
        outcome otherwise. Exceptions in `fatal` (config errors — retrying
        or tripping the breaker cannot fix them) propagate without counting
        as a breaker failure."""
        if not self.allow():
            metrics.inc("breaker.fast_fail", labels={"name": self.name})
            raise CircuitOpenError(self.name, self.retry_in_s())
        try:
            out = fn(*args, **kwargs)
        except fatal:
            with self._lock:
                self._probe_inflight = False
            raise
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
