"""Process-level fault tolerance: the supervisor that owns a multi-process
deployment.

`resilience/supervisor.py` restarts crashed asyncio LOOPS inside one
process; this module graduates the same policy to real OS processes. The
reference system's failure story ends at "one Tokio task per NATS message"
— a crashed service container is simply gone until an operator notices
(PAPER survey §2). Here a deployment is a `ProcessSupervisor` owning:

- the broker (native `symbus_broker` or `python -m symbiont_tpu.bus.pybroker`
  — same wire protocol, same `.symlog` durability, see bus/pybroker.py);
- one `python -m symbiont_tpu.runner` process per worker role
  (SYMBIONT_RUNNER_SERVICES picks the role's service set).

Liveness is judged on THREE signals, because each catches what the others
cannot:

- exit codes — a crashed/killed process is restarted with jittered
  exponential backoff (the supervisor.py policy, per process);
- bus heartbeats (`_sys.heartbeat.<role>`, RunnerConfig.heartbeat_s) — a
  SIGSTOPped or deadlocked worker never exits, but its heartbeats stall;
  past `heartbeat_timeout_s` the supervisor SIGKILLs and restarts it.
  Heartbeat verdicts are GATED on broker health: when the broker itself is
  down, nobody's heartbeats flow, and killing healthy workers for it would
  turn one failure into seven;
- a broker PING probe (raw socket, PONG within a deadline) — the broker
  publishes no heartbeats of its own, and a SIGSTOPped broker still
  accepts TCP connects into its backlog, so only a round-trip proves it
  alive. `/readyz` polling covers the gateway the same way for HTTP.

Durability composes with the planes below: the broker's stream log replays
on restart, `bus/tcp.py` clients auto-reconnect + re-attach durable
consumers, unacked deliveries redeliver after ack_wait, and deterministic
point ids make redelivered work idempotent — so a SIGKILL anywhere in the
deployment (broker included) is a pause, not a loss. Proven end to end by
`python bench.py --only load_multiproc --multiproc` under a seeded kill
plan (bench/load.py) and the chaos scenarios in tests/test_procsup.py.

Metrics: `procsup.up{role}` (1 while the process runs), `procsup.restarts
{role}`, `procsup.heartbeat_age_s{role}`. Restart timestamps are kept on
each worker so a driver can measure kill→serving-again recovery
(`load_proc_recovery_s`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import signal
import socket
import struct
import subprocess
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from symbiont_tpu.utils.retry import jittered
from symbiont_tpu.utils.telemetry import metrics

log = logging.getLogger(__name__)

OP_PING, OP_PONG = 4, 6  # symbus wire opcodes (protocol.hpp)


@dataclass
class WorkerSpec:
    """One supervised process: how to launch it and how to judge it."""

    role: str
    argv: List[str]
    env: Dict[str, str] = field(default_factory=dict)
    # process whose liveness rides bus heartbeats (RunnerConfig.heartbeat_s
    # must be set in env for these); 0 disables the hang detector
    heartbeat_timeout_s: float = 0.0
    # before the FIRST heartbeat ever arrives, judge against this longer
    # window instead: a worker importing jax and building its engine takes
    # far longer to start beating than a live one takes to stall
    boot_grace_s: float = 60.0
    # restart backoff
    backoff_base_s: float = 0.25
    backoff_max_s: float = 10.0
    # the broker worker: probed with a wire PING instead of heartbeats
    is_broker: bool = False
    probe_host: str = "127.0.0.1"
    probe_port: int = 0
    # elastic roles (scale_role): replicas of a base role are workers named
    # "<role>-<i>" carrying the base role here, so the autoscaler can count
    # and retire them as one fleet. Empty = this worker IS its base role.
    base_role: str = ""


class _Worker:
    def __init__(self, spec: WorkerSpec):
        self.spec = spec
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.started_at = 0.0
        self.last_heartbeat = 0.0   # monotonic ts of the last bus heartbeat
        self.up_events: List[float] = []  # heartbeat/probe confirmations
        self.task: Optional[asyncio.Task] = None
        self.stopping = False
        # drain protocol (scale-in): set by _drain_worker — an exit while
        # draining is retirement, never a restart; hang verdicts are
        # suppressed (a flushing worker legitimately stops beating last)
        self.draining = False
        self.drain_clean: Optional[bool] = None  # exited before the deadline?
        # what the worker itself reports in its heartbeat payload
        # (runner._heartbeat_payload: capacity 0 + draining true while the
        # drain protocol runs) — surfaced via procsup.draining / /api/fleet
        self.reported_draining = False
        self.reported_capacity = 1.0
        # restart-storm budget: timestamps of recent restarts; a worker
        # exceeding the storm bound parks in the `crashlooped` state
        # instead of burning CPU on an unbounded backoff loop
        self.restart_times: deque = deque()
        self.crashlooped = False


class ProcessSupervisor:
    """Launch, watch, and restart a set of worker processes.

    The supervisor owns its own bus client (connected lazily once the
    broker answers) purely for the heartbeat subscription — it never
    publishes application traffic.
    """

    def __init__(self, bus_url: str = "", heartbeat_poll_s: float = 0.25,
                 stdio=None, fleet_telemetry: bool = True,
                 fleet_publish_s: float = 2.0,
                 drain_deadline_s: float = 30.0,
                 storm_max_restarts: int = 8, storm_window_s: float = 60.0,
                 crashloop_cooloff_s: float = 300.0):
        self.bus_url = bus_url
        self.heartbeat_poll_s = heartbeat_poll_s
        self.workers: Dict[str, _Worker] = {}
        # drain enforcement (scale_role scale-in): a worker that has not
        # exited this long after the drain request is SIGKILLed — durable
        # redelivery makes even a hung drain lossless
        self.drain_deadline_s = drain_deadline_s
        # restart-storm budget: more than storm_max_restarts restarts
        # inside storm_window_s parks the worker in `crashlooped` (up=0,
        # procsup.crashlooped=1, no respawns) for crashloop_cooloff_s,
        # then allows ONE probe restart with a fresh budget — jittered
        # backoff alone caps at backoff_max_s and burns CPU forever on a
        # permanently-broken argv/env
        self.storm_max_restarts = storm_max_restarts
        self.storm_window_s = storm_window_s
        self.crashloop_cooloff_s = crashloop_cooloff_s
        # scale/drain audit trail consumed by the autoscaler's flap gate
        # and the ramp bench phase: (monotonic ts, base_role, "out"/"in",
        # replica name) appended by scale_role; drain_events records each
        # retirement's outcome as (ts, replica, clean, duration_s) —
        # clean=False means the deadline SIGKILL fired
        self.scale_events: List[tuple] = []
        self.drain_events: List[tuple] = []
        self._bus = None
        self._hb_task: Optional[asyncio.Task] = None
        self._mon_task: Optional[asyncio.Task] = None
        self._stopping = False
        # fleet telemetry plane (obs/fleet.py): the supervisor's own
        # `procsup.*` gauges live in a process with no HTTP server — an
        # exporter publishes them under role "procsup" so the API-role
        # aggregator federates them (the /api/fleet roll-up folds the
        # per-role up/restarts/hangs verdicts, broker probe included, into
        # each supervised role's entry); the supervisor also hosts its OWN
        # aggregator so `sup.fleet.rollup()` answers without any HTTP hop.
        self.fleet_telemetry = fleet_telemetry
        self.fleet_publish_s = fleet_publish_s
        self.fleet = None           # FleetAggregator once the bus is up
        self._fleet_exporter = None
        self._broker_healthy = True
        self._last_probe = 0.0
        # after the broker (re)covers, worker clients reconnect on THEIR
        # jittered exponential backoff (bus/tcp.py: up to several seconds)
        # — suppress hang verdicts for workers that have not yet beaten
        # since the recovery, for this long
        self.broker_resync_grace_s = 10.0
        self._resync_from = 0.0
        self._resync_until = 0.0
        # where worker stdio goes (default: inherit; tests pass DEVNULL or
        # an open log file)
        self._stdio = stdio

    # ------------------------------------------------------------ lifecycle

    def add_worker(self, spec: WorkerSpec) -> None:
        if spec.role in self.workers:
            raise ValueError(f"duplicate worker role {spec.role!r}")
        if not spec.base_role:
            spec = dataclasses.replace(spec, base_role=spec.role)
        self.workers[spec.role] = _Worker(spec)

    async def start(self) -> None:
        self._stopping = False
        loop = asyncio.get_running_loop()
        for w in self.workers.values():
            # executor: Popen's fork+exec is blocking host work (tens of ms
            # on a loaded box) and the supervisor's loop also runs the
            # 0.25s-period liveness probes — never stall them on a spawn
            await loop.run_in_executor(None, self._spawn, w)
            w.task = asyncio.create_task(self._monitor(w),
                                         name=f"procsup-{w.spec.role}")
        if self.bus_url:
            self._hb_task = asyncio.create_task(self._heartbeat_loop(),
                                                name="procsup-heartbeats")

    async def stop(self) -> None:
        self._stopping = True
        for w in self.workers.values():
            w.stopping = True
        if self._fleet_exporter is not None:
            await self._fleet_exporter.stop()
            self._fleet_exporter = None
        if self.fleet is not None:
            await self.fleet.detach()
            self.fleet = None
        if self._hb_task:
            self._hb_task.cancel()
            self._hb_task = None
        if self._bus is not None:
            try:
                await self._bus.close()
            except Exception:
                pass
            self._bus = None
        for w in self.workers.values():
            self._terminate(w, sig=signal.SIGTERM)
        for w in self.workers.values():
            if w.task is not None:
                w.task.cancel()
        tasks = [w.task for w in self.workers.values() if w.task]
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        # grace, then hard kill
        deadline = time.monotonic() + 5.0
        for w in self.workers.values():
            if w.proc is None:
                continue
            while w.proc.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            if w.proc.poll() is None:
                self._terminate(w, sig=signal.SIGKILL)
                # executor: wait() blocks up to its timeout — the loop may
                # still be draining other workers' monitors
                await asyncio.get_running_loop().run_in_executor(
                    None, w.proc.wait, 5)
            metrics.gauge_set("procsup.up", 0,
                              labels={"role": w.spec.role})

    # -------------------------------------------------------------- spawn

    def _spawn(self, w: _Worker) -> None:
        # runs on an executor thread (start/_monitor) — which opens a
        # window where stop() flips the stopping flags while a restart is
        # already past its flag check. Re-check HERE (and once more after
        # the fork below): a supervisor that is stopping must never mint a
        # child it will not be watching.
        if self._stopping or w.stopping:
            return
        env = {**os.environ, **w.spec.env}
        kwargs = {}
        if self._stdio is not None:
            kwargs["stdout"] = self._stdio
            kwargs["stderr"] = self._stdio
        # own process group: a SIGKILL aimed at one worker must never leak
        # to the supervisor's group (and chaos plans kill by pid anyway)
        w.proc = subprocess.Popen(w.spec.argv, env=env,
                                  start_new_session=True, **kwargs)
        if self._stopping or w.stopping:
            # stop() ran while we were forking: its grace/kill loop may
            # already have polled the OLD proc and finished — this child
            # is ours to reap, fully (we are on an executor thread, so the
            # blocking waits are fine), and none of the started-state
            # below may run (the up gauge must stay 0 after stop()).
            self._terminate(w, sig=signal.SIGTERM)
            try:
                w.proc.wait(timeout=5)
            except Exception:
                self._terminate(w, sig=signal.SIGKILL)
                try:
                    w.proc.wait(timeout=5)
                except Exception:
                    pass
            return
        w.started_at = time.monotonic()
        w.last_heartbeat = 0.0
        if w.spec.is_broker:
            # a (re)started broker means every worker's client is about to
            # reconnect on ITS jittered backoff — heartbeats resume at
            # their pace, not ours. Open the resync grace window, or the
            # gap reads as a fleet-wide hang (a restart can also outrun the
            # 1s PING probe, so the broker-unhealthy gate alone is not
            # enough).
            self._note_broker_recovered()
        metrics.gauge_set("procsup.up", 1, labels={"role": w.spec.role})
        log.info("procsup: %s started (pid %d)", w.spec.role, w.proc.pid)

    def _note_broker_recovered(self) -> None:
        now = time.monotonic()
        self._resync_from = now
        self._resync_until = now + self.broker_resync_grace_s

    def _terminate(self, w: _Worker, sig=signal.SIGTERM) -> None:
        if w.proc is None or w.proc.poll() is not None:
            return
        try:
            w.proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass

    def pid(self, role: str) -> Optional[int]:
        w = self.workers[role]
        return None if w.proc is None else w.proc.pid

    def restarts(self, role: str) -> int:
        return self.workers[role].restarts

    # ------------------------------------------------------- elastic scaling

    def replicas(self, base_role: str) -> List[str]:
        """Replica worker names of one base role, base first, then by
        replica index — the retirement order is the reverse (newest
        drains first; the base replica never retires)."""
        names = [name for name, w in self.workers.items()
                 if w.spec.base_role == base_role]
        names.sort(key=lambda n: (n != base_role, len(n), n))
        return names

    def _replica_spec(self, base: WorkerSpec, index: int) -> WorkerSpec:
        """Spec for replica `index` (>= 2) of an elastic role: same argv,
        the role name (and SYMBIONT_RUNNER_ROLE, when the base is a
        runner) suffixed `-<index>` so heartbeats, fleet telemetry and
        the drain subject all address this replica individually, while
        the worker's queue-group subscriptions (named by SERVICE, not by
        role) share the durable streams with its siblings — fan-in is
        free."""
        name = f"{base.base_role or base.role}-{index}"
        env = dict(base.env)
        # always exported (harmless to non-runner workers): the replica
        # must identify as ITSELF on heartbeats and the drain subject
        env["SYMBIONT_RUNNER_ROLE"] = name
        return dataclasses.replace(base, role=name, env=env,
                                   base_role=base.base_role or base.role)

    async def scale_role(self, base_role: str, n: int) -> dict:
        """Resize an elastic role to `n` replicas (the autoscaler's one
        write surface). Scale-out spawns `<role>-<i>` workers joining the
        existing queue groups; scale-in retires the newest replicas
        through the drain protocol (`_drain_worker`): a `_sys.drain`
        request, a deadline, SIGKILL + durable redelivery as the safety
        net. n < 1 is rejected — the base replica always stays. Returns
        {"added": [...], "drained": [...]}."""
        base = self.workers.get(base_role)
        if base is None:
            raise ValueError(f"unknown role {base_role!r}")
        if n < 1:
            raise ValueError("scale_role target must be >= 1 "
                             "(the base replica never retires)")
        names = self.replicas(base_role)
        added: List[str] = []
        drained: List[str] = []
        loop = asyncio.get_running_loop()
        if n > len(names):
            # next replica indices resume past every name ever MINTED (the
            # scale_events audit trail remembers retired ones), so a dead
            # replica's role — whose final draining:true beat can still be
            # in flight — is never reused by a different process
            def _idx(nm: str):
                tail = nm.rsplit("-", 1)[-1]
                return int(tail) if nm != base_role and tail.isdigit() \
                    else None
            used = {i for i in map(_idx, names) if i is not None}
            used |= {i for i in (_idx(ev[3]) for ev in self.scale_events
                                 if ev[1] == base_role) if i is not None}
            idx = 2
            while len(names) + len(added) < n:
                while idx in used:
                    idx += 1
                spec = self._replica_spec(base.spec, idx)
                used.add(idx)
                self.add_worker(spec)
                w = self.workers[spec.role]
                await loop.run_in_executor(None, self._spawn, w)
                w.task = asyncio.create_task(
                    self._monitor(w), name=f"procsup-{w.spec.role}")
                added.append(spec.role)
                metrics.inc("procsup.scale_out",
                            labels={"role": base_role})
                log.info("procsup: scale-out %s -> %s", base_role,
                         spec.role)
                self.scale_events.append(
                    (time.monotonic(), base_role, "out", spec.role))
        elif n < len(names):
            for name in reversed(names[n:]):  # newest retires first
                metrics.inc("procsup.scale_in", labels={"role": base_role})
                self.scale_events.append(
                    (time.monotonic(), base_role, "in", name))
                await self._drain_worker(self.workers[name])
                drained.append(name)
        return {"added": added, "drained": drained}

    async def _drain_worker(self, w: _Worker,
                            deadline_s: Optional[float] = None) -> None:
        """Retire one worker through the drain protocol: publish
        `_sys.drain.<role>` (the worker detaches its durable consumers,
        flushes its coalescer, finishes in-flight work, beats
        `draining: true`, and exits rc 0), enforce the deadline, SIGKILL a
        hung drain (its unacked deliveries redeliver — still lossless),
        and remove the worker from supervision."""
        from symbiont_tpu import subjects

        role = w.spec.role
        w.draining = True
        t_drain = time.monotonic()
        metrics.gauge_set("procsup.draining", 1, labels={"role": role})
        deadline_s = self.drain_deadline_s if deadline_s is None \
            else deadline_s
        sent = False
        if self._bus is not None:
            try:
                await self._bus.publish(f"{subjects.SYS_DRAIN}.{role}",
                                        b"{}")
                sent = True
            except Exception:
                log.warning("procsup: drain publish for %s failed", role)
        if not sent:
            # no bus (broker down, or a bus-less supervisor): SIGTERM is
            # the degraded drain — the runner's signal handler stops the
            # stack, whose service stops still flush-on-stop
            self._terminate(w, sig=signal.SIGTERM)
        loop = asyncio.get_running_loop()
        deadline = time.monotonic() + deadline_s
        # a publish to a subject nobody subscribes SUCCEEDS (C++ shells
        # have no drain subscription yet): if the worker neither exits nor
        # reports draining within a grace, escalate to SIGTERM so its
        # graceful-terminate path runs instead of burning the whole
        # deadline into a SIGKILL (common.hpp's promised fallback)
        term_at = time.monotonic() + min(5.0, deadline_s / 3.0) \
            if sent else None
        while time.monotonic() < deadline:
            if w.proc is None or w.proc.poll() is not None:
                break
            if (term_at is not None and not w.reported_draining
                    and time.monotonic() >= term_at):
                term_at = None
                log.info("procsup: %s never acknowledged the bus drain; "
                         "escalating to SIGTERM", role)
                self._terminate(w, sig=signal.SIGTERM)
            await asyncio.sleep(0.05)
        w.drain_clean = w.proc is None or w.proc.poll() is not None
        if not w.drain_clean:
            # the safety net: a hung drain still loses nothing — its
            # durable deliveries were never acked and redeliver to the
            # surviving replicas after ack_wait
            metrics.inc("procsup.drain_timeouts", labels={"role": role})
            log.warning("procsup: %s drain exceeded %.1fs; SIGKILL "
                        "(durable redelivery covers its in-flight work)",
                        role, deadline_s)
            self._terminate(w, sig=signal.SIGKILL)
            # durable redelivery only covers un-acked QUEUE work; a
            # mid-stream generation is past its ack — the journal is its
            # only recovery. Republish its tails to a surviving replica.
            await self._rescue_gen_orphans(w)
        w.stopping = True
        if w.task is not None:
            w.task.cancel()
            await asyncio.gather(w.task, return_exceptions=True)
            w.task = None
        if w.proc is not None:
            # reap off-loop; bounded — a zombie wait can't stall siblings
            try:
                await loop.run_in_executor(None, w.proc.wait, 10)
            except Exception:
                pass
        metrics.gauge_set("procsup.up", 0, labels={"role": role})
        metrics.gauge_set("procsup.draining", 0, labels={"role": role})
        log.info("procsup: %s drained (%s)", role,
                 "clean" if w.drain_clean else "deadline -> SIGKILL")
        self.drain_events.append((time.monotonic(), role, w.drain_clean,
                                  round(time.monotonic() - t_drain, 3)))
        self.workers.pop(role, None)

    # ------------------------------------------------ gen-session rescue

    async def _rescue_gen_orphans(self, w: _Worker) -> None:
        """Durable-generation recovery (docs/RESILIENCE.md): when a worker
        with the gen journal enabled dies mid-stream, scan its journal for
        live session tails, rotate the file aside, and republish each tail
        as a `tasks.generation.resume` task — the text-generator queue
        group picks exactly one surviving replica to adopt each stream.
        No-op for workers without SYMBIONT_GEN_JOURNAL_ENABLED in env.
        Requires the supervisor's bus: with the broker down, the file is
        left IN PLACE (unrotated) so a later death verdict — or the
        restarted role's own survivor reload — still covers it."""
        from symbiont_tpu import subjects
        from symbiont_tpu.config import GenJournalConfig
        from symbiont_tpu.resilience.genlog import GenJournal

        env = w.spec.env
        if env.get("SYMBIONT_GEN_JOURNAL_ENABLED", "").lower() not in (
                "1", "true", "yes", "on"):
            return
        if self._bus is None:
            log.warning("procsup: %s died with a gen journal but the bus "
                        "is down; deferring the orphan scan", w.spec.role)
            return
        role = env.get("SYMBIONT_RUNNER_ROLE", w.spec.role)
        jdir = env.get("SYMBIONT_GEN_JOURNAL_DIR", GenJournalConfig().dir)
        path = os.path.join(jdir, f"{role}.genlog")
        # blocking file I/O (scan + rotate) off the supervisor loop — the
        # sibling monitors and the broker probe keep their 0.25s cadence
        try:
            tails = await asyncio.get_running_loop().run_in_executor(
                None, GenJournal.take_orphans, path)
        except Exception:
            log.warning("procsup: gen journal scan for %s failed",
                        w.spec.role, exc_info=True)
            return
        if not tails:
            return
        metrics.inc("gen.orphans", len(tails))
        log.warning("procsup: %s left %d orphaned generation session(s); "
                    "republishing for adoption", w.spec.role, len(tails))
        for task_id, rec in tails.items():
            body = json.dumps({"task_id": task_id, "record": rec,
                               "attempt": 0}).encode()
            try:
                await self._bus.publish(subjects.TASKS_GENERATION_RESUME,
                                        body)
            except Exception:
                log.warning("procsup: resume publish for %s failed",
                            task_id, exc_info=True)

    # ----------------------------------------------------------- liveness

    async def _monitor(self, w: _Worker) -> None:
        """Exit-code + hang supervision for one worker, with jittered
        exponential backoff between restarts (supervisor.py policy) and
        the restart-storm budget (crashloop parking)."""
        delay = w.spec.backoff_base_s
        while not self._stopping and not w.stopping:
            rc = w.proc.poll() if w.proc is not None else None
            if w.draining:
                # retirement in progress (_drain_worker owns the deadline
                # + SIGKILL): an exit now is the PROTOCOL, not a crash —
                # never restart, never judge hangs
                if rc is None:
                    await asyncio.sleep(self.heartbeat_poll_s)
                    continue
                metrics.gauge_set("procsup.up", 0,
                                  labels={"role": w.spec.role})
                return
            if rc == 0 and w.reported_draining:
                # a drain the supervisor did not initiate (operator-
                # published `_sys.drain.<role>`): the worker's last beat
                # announced the retirement and it exited clean — honoring
                # it beats respawning a process someone asked to go away.
                # REMOVED from supervision like a scale_role drain, so the
                # autoscaler/fleet stop counting a dead process as a live
                # serving replica
                log.info("procsup: %s retired after a self-reported drain",
                         w.spec.role)
                metrics.gauge_set("procsup.up", 0,
                                  labels={"role": w.spec.role})
                metrics.gauge_set("procsup.draining", 0,
                                  labels={"role": w.spec.role})
                self.drain_events.append(
                    (time.monotonic(), w.spec.role, True, 0.0))
                self.workers.pop(w.spec.role, None)
                return
            hung = self._is_hung(w)
            if rc is None and not hung:
                # healthy run resets the backoff after a stable period
                if time.monotonic() - w.started_at > 10 * delay:
                    delay = w.spec.backoff_base_s
                await asyncio.sleep(self.heartbeat_poll_s)
                continue
            if rc is None:
                # hung (heartbeats stalled / probe dead): only SIGKILL
                # clears a SIGSTOPped process
                log.warning("procsup: %s HUNG (no liveness signal for "
                            "%.1fs); killing pid %d", w.spec.role,
                            time.monotonic() - max(w.last_heartbeat,
                                                   w.started_at),
                            w.proc.pid)
                metrics.inc("procsup.hangs", labels={"role": w.spec.role})
                self._terminate(w, sig=signal.SIGKILL)
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        None, w.proc.wait, 10)
                except Exception:
                    pass
            else:
                log.warning("procsup: %s exited rc=%s", w.spec.role, rc)
            metrics.gauge_set("procsup.up", 0, labels={"role": w.spec.role})
            # the worker is CONFIRMED dead (exit or hang SIGKILL): rescue
            # any generation sessions its journal left mid-stream before
            # the restart — the restarted process must start from a fresh
            # journal, and a surviving replica adopts the streams
            await self._rescue_gen_orphans(w)
            if self._stopping or w.stopping:
                return
            if not await self._respect_storm_budget(w):
                return  # stop() interrupted the crashloop cool-off
            await asyncio.sleep(jittered(delay))
            delay = min(delay * 2, w.spec.backoff_max_s)
            if self._stopping or w.stopping:
                return
            w.restarts += 1
            w.restart_times.append(time.monotonic())
            metrics.inc("procsup.restarts", labels={"role": w.spec.role})
            # executor, like start(): a restart storm must not freeze the
            # sibling monitors and the broker probe behind serial forks
            await asyncio.get_running_loop().run_in_executor(
                None, self._spawn, w)

    async def _respect_storm_budget(self, w: _Worker) -> bool:
        """The restart-storm budget: a worker past `storm_max_restarts`
        restarts inside `storm_window_s` PARKS in the `crashlooped` state
        (up=0, `procsup.crashlooped{role}`=1, surfaced in /api/fleet) for
        `crashloop_cooloff_s` instead of burning CPU on fork/exec forever
        — jittered backoff alone is bounded per cycle, not per hour. After
        the cool-off, ONE probe restart runs with a fresh budget. Returns
        False when stop() interrupted the wait."""
        now = time.monotonic()
        while w.restart_times and now - w.restart_times[0] \
                > self.storm_window_s:
            w.restart_times.popleft()
        if len(w.restart_times) < self.storm_max_restarts:
            return True
        w.crashlooped = True
        metrics.gauge_set("procsup.crashlooped", 1,
                          labels={"role": w.spec.role})
        log.error("procsup: %s CRASHLOOPED (%d restarts in %.0fs); parked "
                  "for %.0fs", w.spec.role, len(w.restart_times),
                  self.storm_window_s, self.crashloop_cooloff_s)
        deadline = now + self.crashloop_cooloff_s
        while time.monotonic() < deadline:
            if self._stopping or w.stopping:
                return False
            await asyncio.sleep(min(0.5, self.heartbeat_poll_s * 2))
        w.crashlooped = False
        w.restart_times.clear()
        metrics.gauge_set("procsup.crashlooped", 0,
                          labels={"role": w.spec.role})
        log.warning("procsup: %s cool-off elapsed; probing one restart",
                    w.spec.role)
        return True

    def _is_hung(self, w: _Worker) -> bool:
        if w.spec.is_broker:
            return False  # judged by the probe loop (needs a round-trip)
        if w.draining:
            # a draining worker detaches its consumers and may stop
            # beating while it flushes: the DRAIN deadline (not the hang
            # detector) is its bound
            return False
        if w.spec.heartbeat_timeout_s <= 0:
            return False
        if not self._broker_healthy:
            # the broker is down/SIGSTOPped: NOBODY's heartbeats flow.
            # Judging workers now would turn one failure into many.
            return False
        if w.last_heartbeat == 0.0:
            # never beaten yet: still booting (jax import + engine build) —
            # judge against the boot grace, not the steady-state timeout
            return (time.monotonic() - w.started_at) > w.spec.boot_grace_s
        now = time.monotonic()
        if now < self._resync_until and w.last_heartbeat < self._resync_from:
            # broker just recovered and this worker hasn't beaten through
            # it yet: its client is mid-reconnect, not hung
            return False
        age = time.monotonic() - w.last_heartbeat
        metrics.gauge_set("procsup.heartbeat_age_s", round(age, 2),
                          labels={"role": w.spec.role})
        return age > w.spec.heartbeat_timeout_s

    async def _heartbeat_loop(self) -> None:
        """Subscribe `_sys.heartbeat.>` on the broker and stamp workers;
        also probes the broker itself (PING→PONG round-trip) and flips
        `_broker_healthy`, SIGKILLing a hung broker so its monitor
        restarts it over the persisted stream log."""
        from symbiont_tpu import subjects
        from symbiont_tpu.bus import connect

        sub = None
        while not self._stopping:
            # (re)connect the supervisor's own bus client
            if self._bus is None:
                try:
                    # retries=1: this loop IS the retry policy (fast poll)
                    self._bus = await connect(self.bus_url, retries=1)
                    sub = await self._bus.subscribe(
                        subjects.SYS_HEARTBEAT + ".>")
                    if self.fleet_telemetry:
                        await self._start_fleet_telemetry()
                except (ConnectionError, OSError):
                    self._bus = None
                    await asyncio.sleep(self.heartbeat_poll_s)
                    continue
            msg = await sub.next(self.heartbeat_poll_s)
            now = time.monotonic()
            if msg is not None:
                role = msg.subject.rsplit(".", 1)[-1]
                w = self.workers.get(role)
                if w is not None:
                    w.last_heartbeat = now
                    w.up_events.append(now)
                    del w.up_events[:-64]
                    self._note_heartbeat_payload(w, msg.data)
            await self._probe_broker()

    @staticmethod
    def _note_heartbeat_payload(w: _Worker, data: bytes) -> None:
        """Fold the beat's capacity/draining fields (runner
        `_heartbeat_payload`) into the worker's state: a worker reporting
        `draining: true` is mid-retirement — the roll-up shows it and the
        autoscaler stops counting it as serving headroom. Pre-field beats
        (C++ shells on an old image, the toy test workers' `{}`) read as
        serving at full capacity."""
        try:
            hb = json.loads(data) if data else {}
        except ValueError:
            hb = {}
        if not isinstance(hb, dict):
            return
        w.reported_draining = bool(hb.get("draining", False))
        try:
            w.reported_capacity = float(hb.get("capacity", 1))
        except (TypeError, ValueError):
            w.reported_capacity = 1.0
        metrics.gauge_set("procsup.draining",
                          1 if (w.reported_draining or w.draining) else 0,
                          labels={"role": w.spec.role})

    async def _start_fleet_telemetry(self) -> None:
        """Attach the supervisor's fleet aggregator to the (re)connected
        bus and start its own `procsup`-role exporter (once — the exporter
        reads the live bus through a closure, so reconnects are free)."""
        from symbiont_tpu.obs.fleet import (
            FleetAggregator,
            TelemetryExporter,
            subscribe_telemetry,
        )

        if self.fleet is None:
            self.fleet = FleetAggregator(local_role="procsup")
        self.fleet.attach(await subscribe_telemetry(self._bus))
        if self._fleet_exporter is None:
            self._fleet_exporter = TelemetryExporter(
                lambda: self._bus, role="procsup",
                publish_s=self.fleet_publish_s)
            self._fleet_exporter.start()

    async def _probe_broker(self) -> None:
        """PING→PONG the broker over a fresh socket. A SIGSTOPped broker
        still ACCEPTS connections (kernel backlog) — only the round-trip
        proves the event loop is alive."""
        broker = next((w for w in self.workers.values()
                       if w.spec.is_broker), None)
        if broker is None:
            return
        now = time.monotonic()
        if now - self._last_probe < 1.0:
            return
        self._last_probe = now
        timeout = max(1.0, broker.spec.heartbeat_timeout_s or 3.0)
        ok = await asyncio.get_running_loop().run_in_executor(
            None, self._ping_once,
            broker.spec.probe_host, broker.spec.probe_port, timeout)
        if ok and not self._broker_healthy:
            # broker just came back (e.g. SIGCONT after a SIGSTOP — no
            # respawn involved): same resync grace as a restart
            self._note_broker_recovered()
        self._broker_healthy = ok
        metrics.gauge_set("procsup.up",
                          1 if (ok and broker.proc is not None
                                and broker.proc.poll() is None) else 0,
                          labels={"role": broker.spec.role})
        if ok:
            broker.last_heartbeat = now
            broker.up_events.append(now)
            del broker.up_events[:-64]
        elif (broker.proc is not None and broker.proc.poll() is None
              and broker.spec.heartbeat_timeout_s > 0
              and now - max(broker.last_heartbeat,
                            broker.started_at)
              > broker.spec.heartbeat_timeout_s):
            # alive by exit code, dead by probe: SIGSTOPped/deadlocked —
            # kill it; the monitor restarts it over the persisted log
            log.warning("procsup: broker %s unresponsive to PING; killing",
                        broker.spec.role)
            metrics.inc("procsup.hangs", labels={"role": broker.spec.role})
            self._terminate(broker, sig=signal.SIGKILL)

    @staticmethod
    def _ping_once(host: str, port: int, timeout_s: float) -> bool:
        try:
            with socket.create_connection((host, port),
                                          timeout=timeout_s) as s:
                s.settimeout(timeout_s)
                s.sendall(struct.pack("<IB", 1, OP_PING))
                head = b""
                while len(head) < 5:
                    chunk = s.recv(5 - len(head))
                    if not chunk:
                        return False
                    head += chunk
                n, op = struct.unpack("<IB", head)
                return op == OP_PONG
        except OSError:
            return False

    # ------------------------------------------------- recovery measurement

    async def wait_role_up(self, role: str, after: float,
                           timeout_s: float = 60.0) -> float:
        """Block until `role` shows a liveness confirmation (heartbeat or
        broker-probe success) AFTER monotonic time `after`; returns that
        confirmation's timestamp. The kill→serving-again measurement behind
        `load_proc_recovery_s`."""
        deadline = time.monotonic() + timeout_s
        w = self.workers[role]
        while time.monotonic() < deadline:
            for ts in w.up_events:
                if ts > after:
                    return ts
            await asyncio.sleep(0.05)
        raise TimeoutError(
            f"role {role!r} showed no liveness signal within {timeout_s}s "
            f"of the kill (restarts={w.restarts})")


# ----------------------------------------------------------------- helpers


def runner_spec(role: str, services: str, bus_url: str,
                env: Optional[Dict[str, str]] = None,
                heartbeat_s: float = 0.5,
                heartbeat_timeout_s: float = 5.0) -> WorkerSpec:
    """A WorkerSpec for one `python -m symbiont_tpu.runner` role."""
    full_env = {
        "SYMBIONT_BUS_URL": bus_url,
        "SYMBIONT_RUNNER_SERVICES": services,
        "SYMBIONT_RUNNER_ROLE": role,
        "SYMBIONT_RUNNER_HEARTBEAT_S": str(heartbeat_s),
        **(env or {}),
    }
    return WorkerSpec(role=role,
                      argv=[sys.executable, "-m", "symbiont_tpu.runner"],
                      env=full_env,
                      heartbeat_timeout_s=heartbeat_timeout_s)


def pybroker_spec(port: int, data_dir: str, role: str = "broker",
                  heartbeat_timeout_s: float = 5.0) -> WorkerSpec:
    """A WorkerSpec for the pure-Python broker (bus/pybroker.py)."""
    return WorkerSpec(
        role=role,
        argv=[sys.executable, "-m", "symbiont_tpu.bus.pybroker",
              "--host", "127.0.0.1", "--port", str(port),
              "--data-dir", data_dir],
        is_broker=True, probe_port=port,
        heartbeat_timeout_s=heartbeat_timeout_s,
        # a fresh broker replays its log in well under a second; restart
        # fast so redelivery windows stay short
        backoff_base_s=0.2, backoff_max_s=2.0)
