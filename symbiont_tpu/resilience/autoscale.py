"""SLO-driven elastic autoscaling: role-split fleets that grow, shrink,
and drain safely under traffic ramps.

The reference system's "evolving organism" runs every service as exactly
one container forever; PR 10's ProcessSupervisor can RESTART roles but not
RESIZE them, so a traffic ramp ends in the shed ladder
(resilience/admission.py) instead of more capacity. This module closes
ROADMAP item 3's serving half: an `Autoscaler` attached to the supervisor
consumes the pressure signals the admission plane and fleet telemetry
already measure —

- per-role engine queue depth (`batcher.queue_depth` /
  `batcher.tenant_depth` gauges, federated over `_sys.telemetry.metrics.*`
  by obs/fleet.py),
- KV occupancy for decode roles (`lm.kv_rows_allocated` vs
  `autoscale.kv_high_rows`),
- SLO-watchdog breach counts and shed-ladder activity
  (`slo.breaches`, `admission.shed` — gateway-side, global pressure),

and drives `ProcessSupervisor.scale_role(role, n)`:

- **scale-out** spawns additional replicas (`embed-2`, `embed-3`, …) that
  join the existing queue groups — durable queue-group delivery shards the
  work with zero routing changes;
- **scale-in** retires the newest replica through the first-class **drain
  protocol**: the supervisor publishes `_sys.drain.<role>`, the worker
  stops pulling new durable deliveries (consumers DETACH, so unacked work
  redelivers to the survivors), flushes its `UpsertCoalescer`
  (ack-after-flush waits release), finishes in-flight generation, beats
  `draining: true` once, and exits rc 0. The supervisor enforces
  `drain_deadline_s`; a hung drain is SIGKILLed and durable redelivery
  still loses nothing (proven by tests/test_autoscale.py `-m chaos` and
  the `load_ramp` bench phase).

Scaling decisions carry breaker-style hysteresis (the DegradationLadder
shape: dwell both directions + `in_clean_passes` consecutive clean passes
to shrink) plus a global `OpsBudget`, so a flapping signal or a
crash-looping role cannot thrash the box — the supervisor's own
restart-storm budget (`crashlooped` parking) covers the restart half.

Nothing here imports jax or any service module; the signal reader and the
clock are injectable so the policy is unit-testable without processes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from symbiont_tpu.utils.telemetry import metrics

log = logging.getLogger(__name__)

# flat-snapshot key prefixes (obs/fleet.py role snapshots) the default
# signal reader folds into per-role pressure
_DEPTH_PREFIX = "gauge.batcher.queue_depth"
_LANE_PREFIX = "gauge.batcher.tenant_depth"
_KV_PREFIX = "gauge.lm.kv_rows_allocated"
# gateway-side counters whose GROWTH is global "capacity is short" evidence
_GLOBAL_PREFIXES = ("counter.slo.breaches", "counter.admission.shed")


@dataclass(frozen=True)
class RoleBounds:
    """Replica bounds of one elastic role ("embed=1:4")."""

    min: int
    max: int


def parse_role_bounds(spec: str) -> Dict[str, RoleBounds]:
    """`"embed=1:4,decode=1:2"` → {"embed": RoleBounds(1, 4), ...}.
    Raises ValueError on malformed entries — a typo'd bound must fail at
    boot, not silently never scale. min >= 1 (the base replica never
    retires), max >= min."""
    out: Dict[str, RoleBounds] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, raw = entry.partition("=")
        name = name.strip()
        lo, sep2, hi = raw.partition(":")
        if not sep or not sep2 or not name:
            raise ValueError(
                f"autoscale role {entry!r} must look like 'role=min:max'")
        try:
            bounds = RoleBounds(int(lo), int(hi))
        except ValueError:
            raise ValueError(
                f"autoscale role {entry!r}: bounds must be integers"
            ) from None
        if bounds.min < 1 or bounds.max < bounds.min:
            raise ValueError(
                f"autoscale role {entry!r}: need 1 <= min <= max")
        out[name] = bounds
    return out


class OpsBudget:
    """Global scale/restart budget: at most `max_ops` operations per
    sliding `window_s`. One budget covers every role and both directions —
    the box-thrash bound, not a fairness mechanism."""

    def __init__(self, max_ops: int, window_s: float,
                 clock: Callable[[], float] = time.monotonic):
        if max_ops < 1 or window_s <= 0:
            raise ValueError("budget max_ops >= 1 and window_s > 0")
        self.max_ops = int(max_ops)
        self.window_s = float(window_s)
        self._clock = clock
        self._ops: deque = deque()

    def try_take(self) -> bool:
        now = self._clock()
        while self._ops and now - self._ops[0] > self.window_s:
            self._ops.popleft()
        if len(self._ops) >= self.max_ops:
            return False
        self._ops.append(now)
        return True

    def remaining(self) -> int:
        now = self._clock()
        while self._ops and now - self._ops[0] > self.window_s:
            self._ops.popleft()
        return self.max_ops - len(self._ops)


@dataclass
class RoleSignals:
    """One role's pressure inputs for one evaluation pass."""

    # engine queue depth per LIVE replica (the averaged federated gauges)
    queue_depth: float = 0.0
    # allocated KV rows per live replica (decode roles)
    kv_rows: float = 0.0
    # global capacity-shortfall evidence this pass (SLO breach / shed
    # counters grew since the previous pass)
    breach: bool = False


class FleetSignalReader:
    """Default signal source: the supervisor's FleetAggregator role
    snapshots (obs/fleet.py). Per elastic role it averages the engine
    queue-depth and KV gauges over that role's live replicas, and turns
    gateway-side `slo.breaches` / `admission.shed` counter GROWTH into the
    global breach flag. Stateless callers can inject any
    `fn(bounds) -> {role: RoleSignals}` instead."""

    def __init__(self, sup):
        self.sup = sup
        self._last_global = 0.0

    def _snapshots(self) -> Dict[str, Dict[str, float]]:
        fleet = getattr(self.sup, "fleet", None)
        return {} if fleet is None else fleet.role_snapshots()

    @staticmethod
    def _sum_prefix(snap: Dict[str, float], prefix: str) -> float:
        return sum(v for k, v in snap.items() if k.startswith(prefix))

    def __call__(self, bounds: Dict[str, RoleBounds]
                 ) -> Dict[str, RoleSignals]:
        snaps = self._snapshots()
        total_global = sum(self._sum_prefix(snap, p)
                           for snap in snaps.values()
                           for p in _GLOBAL_PREFIXES)
        breach = total_global > self._last_global
        self._last_global = total_global
        out: Dict[str, RoleSignals] = {}
        for role in bounds:
            depth = kv = 0.0
            live = 0
            for name in self.sup.replicas(role):
                w = self.sup.workers.get(name)
                if w is None or w.draining:
                    continue
                live += 1
                snap = snaps.get(name, {})
                d = self._sum_prefix(snap, _DEPTH_PREFIX)
                if d == 0.0:
                    # pre-queue_depth gauges (or a batcher-less role):
                    # the per-tenant lane depths are the same backlog
                    d = self._sum_prefix(snap, _LANE_PREFIX)
                depth += d
                kv += self._sum_prefix(snap, _KV_PREFIX)
            live = max(1, live)
            out[role] = RoleSignals(queue_depth=depth / live,
                                    kv_rows=kv / live, breach=breach)
        return out


class _RoleState:
    def __init__(self, now: float, out_dwell_s: float):
        # first pressure pass acts immediately (DegradationLadder stance)
        self.last_change = now - out_dwell_s
        self.clean = 0


class Autoscaler:
    """The policy loop: every `cfg.eval_s`, read signals, apply
    hysteresis + the global budget, and call `sup.scale_role`. Decisions
    are recorded in `self.decisions` (monotonic ts, role, "out"/"in",
    target) — the flap gate and the ramp bench phase read them."""

    def __init__(self, sup, cfg=None,
                 signals: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        from symbiont_tpu.config import AutoscaleConfig

        self.sup = sup
        self.cfg = cfg or AutoscaleConfig()
        self.bounds = parse_role_bounds(self.cfg.roles)
        self.signals = signals or FleetSignalReader(sup)
        self._clock = clock
        self.budget = OpsBudget(self.cfg.budget_ops,
                                self.cfg.budget_window_s, clock)
        now = clock()
        self._state = {role: _RoleState(now, self.cfg.out_dwell_s)
                       for role in self.bounds}
        self.decisions: list = []
        self._task: Optional[asyncio.Task] = None
        # the drain deadline is policy, enforced by the supervisor
        sup.drain_deadline_s = self.cfg.drain_deadline_s
        metrics.inc("autoscale.budget_exhausted", 0)
        for role in self.bounds:
            metrics.gauge_set("autoscale.pressure", 0.0,
                              labels={"role": role})
            metrics.gauge_set("autoscale.replicas",
                              len(sup.replicas(role)) or 1,
                              labels={"role": role})

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="autoscaler")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.eval_s)
            try:
                await self.evaluate_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                metrics.inc("autoscale.errors")
                log.exception("autoscale evaluation failed")

    # --------------------------------------------------------------- policy

    def _pressure(self, sig: RoleSignals) -> float:
        p = sig.queue_depth / self.cfg.queue_high
        if self.cfg.kv_high_rows > 0:
            p = max(p, sig.kv_rows / self.cfg.kv_high_rows)
        if sig.breach:
            p = max(p, 1.0)
        return p

    def _clean(self, sig: RoleSignals) -> bool:
        if sig.breach or sig.queue_depth > self.cfg.queue_low:
            return False
        return (self.cfg.kv_high_rows <= 0
                or sig.kv_rows <= 0.5 * self.cfg.kv_high_rows)

    def flaps(self) -> int:
        """Direction reversals inside one hysteresis window — the no-flap
        hard gate of the ramp bench phase. Dwell enforcement makes this 0
        by construction; the gate proves the enforcement held."""
        last: Dict[str, tuple] = {}
        n = 0
        for ts, role, direction, _target in self.decisions:
            prev = last.get(role)
            window = (self.cfg.in_dwell_s if direction == "in"
                      else self.cfg.out_dwell_s)
            if prev is not None and prev[1] != direction \
                    and ts - prev[0] < window:
                n += 1
            last[role] = (ts, direction)
        return n

    async def evaluate_once(self) -> None:
        """One policy pass. Skipped entirely while the broker is
        unhealthy: every signal is stale then, and a drain request could
        not even be published — scaling on a dead bus is exactly the
        thrash the budget exists to prevent."""
        if not getattr(self.sup, "_broker_healthy", True):
            metrics.inc("autoscale.skipped_broker_down")
            return
        sigs = self.signals(self.bounds)
        now = self._clock()
        for role, bounds in self.bounds.items():
            sig = sigs.get(role)
            if sig is None:
                continue
            live = [n for n in self.sup.replicas(role)
                    if n in self.sup.workers
                    and not self.sup.workers[n].draining]
            cur = len(live)
            if cur == 0:
                continue  # base replica mid-restart: nothing to resize
            p = self._pressure(sig)
            st = self._state[role]
            metrics.gauge_set("autoscale.pressure", round(p, 3),
                              labels={"role": role})
            metrics.gauge_set("autoscale.replicas", cur,
                              labels={"role": role})
            if p >= 1.0:
                st.clean = 0
                if cur >= bounds.max:
                    continue
                if now - st.last_change < self.cfg.out_dwell_s:
                    continue
                if not self.budget.try_take():
                    metrics.inc("autoscale.budget_exhausted")
                    log.warning("autoscale: %s pressure %.2f but the "
                                "global scale budget is exhausted", role, p)
                    continue
                st.last_change = now
                target = cur + 1
                self.decisions.append((now, role, "out", target))
                metrics.inc("autoscale.decisions",
                            labels={"role": role, "direction": "out"})
                log.info("autoscale: %s -> %d replicas (pressure %.2f)",
                         role, target, p)
                await self.sup.scale_role(role, target)
            elif self._clean(sig):
                st.clean += 1
                if cur <= bounds.min:
                    continue
                if st.clean < self.cfg.in_clean_passes:
                    continue
                if now - st.last_change < self.cfg.in_dwell_s:
                    continue
                if not self.budget.try_take():
                    metrics.inc("autoscale.budget_exhausted")
                    continue
                st.last_change = now
                st.clean = 0
                target = cur - 1
                self.decisions.append((now, role, "in", target))
                metrics.inc("autoscale.decisions",
                            labels={"role": role, "direction": "in"})
                log.info("autoscale: %s -> %d replicas (drain scale-in, "
                         "%d clean passes)", role, target,
                         self.cfg.in_clean_passes)
                await self.sup.scale_role(role, target)
            else:
                # neither hot nor clean: the dead band — hold, and reset
                # the clean streak so a noisy signal never shrinks
                st.clean = 0
