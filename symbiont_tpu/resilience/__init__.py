"""Resilience plane (SURVEY.md §5.3: the reference's one failure policy is
log-and-drop).

Modules:
- faults:     deterministic seeded fault injection at the bus/store seams
              (the chaos-test harness; a no-op unless a plan is active);
- breaker:    circuit breakers with closed/open/half-open states and
              `breaker.*` gauges;
- dlq:        bounded dead-letter quarantine store behind `GET /api/dlq`;
- stores:     breaker + WAL-spill wrappers over the vector/graph backends
              (graceful degradation: an outage spills writes locally and
              replays them on recovery);
- supervisor: restart-with-backoff for long-lived service loop tasks.

docs/RESILIENCE.md carries the fault → layer → policy → metric matrix.
"""

from symbiont_tpu.resilience.breaker import CircuitBreaker, CircuitOpenError
from symbiont_tpu.resilience.dlq import DeadLetterStore
from symbiont_tpu.resilience.faults import FaultInjected, FaultPlan, FaultRule

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadLetterStore",
    "FaultInjected",
    "FaultPlan",
    "FaultRule",
]
