"""Dead-letter quarantine: the bounded, inspectable store behind
`GET /api/dlq`.

A durable delivery that exhausts `max_deliver` is poison — redelivering it
forever would wedge the consumer group (SURVEY.md §5.3: the reference's
answer is to drop it on the floor). Instead the inproc durable layer
publishes it to `dlq.<original-subject>` with failure headers AND parks the
full message here, where an operator can list, inspect, and replay it after
fixing the handler.

Bounded ring (oldest quarantined entry evicted first, with a counter — a
poison flood must not OOM the process). Metrics: `dlq.quarantined` /
`dlq.replayed` / `dlq.evicted` counters (subject-labeled) and a `dlq.size`
gauge.
"""

from __future__ import annotations

import base64
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from symbiont_tpu.utils.ids import current_timestamp_ms
from symbiont_tpu.utils.telemetry import metrics

# headers stamped on the dlq.<subject> publication and on replayed messages
REASON_HEADER = "X-Symbiont-DLQ-Reason"
STREAM_HEADER = "X-Symbiont-DLQ-Stream"
GROUP_HEADER = "X-Symbiont-DLQ-Group"
DELIVERIES_HEADER = "X-Symbiont-DLQ-Deliveries"
REPLAY_HEADER = "X-Symbiont-Replayed"


@dataclass
class DeadLetter:
    id: int
    subject: str
    data: bytes
    headers: Dict[str, str]
    reason: str
    stream: str
    group: str
    deliveries: int
    quarantined_at_ms: int = field(default_factory=current_timestamp_ms)

    def summary(self, preview_bytes: int = 256) -> dict:
        """JSON-safe view: payload as a bounded UTF-8 preview plus full
        base64 (binary payloads must survive the round trip)."""
        return {
            "id": self.id,
            "subject": self.subject,
            "reason": self.reason,
            "stream": self.stream,
            "group": self.group,
            "deliveries": self.deliveries,
            "quarantined_at_ms": self.quarantined_at_ms,
            "data_preview": self.data[:preview_bytes].decode(
                "utf-8", errors="replace"),
            "data_b64": base64.b64encode(self.data).decode("ascii"),
            "headers": dict(self.headers),
        }


class DeadLetterStore:
    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError("dlq capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[int, DeadLetter]" = OrderedDict()
        self._next_id = 1

    def quarantine(self, subject: str, data: bytes,
                   headers: Optional[Dict[str, str]], *, reason: str,
                   stream: str = "", group: str = "",
                   deliveries: int = 0) -> DeadLetter:
        with self._lock:
            entry = DeadLetter(self._next_id, subject, bytes(data),
                               dict(headers or {}), reason, stream, group,
                               deliveries)
            self._next_id += 1
            self._entries[entry.id] = entry
            while len(self._entries) > self.capacity:
                old_id, old = self._entries.popitem(last=False)
                metrics.inc("dlq.evicted", labels={"subject": old.subject})
            size = len(self._entries)
        metrics.inc("dlq.quarantined", labels={"subject": subject})
        metrics.gauge_set("dlq.size", size)
        return entry

    def get(self, entry_id: int) -> Optional[DeadLetter]:
        with self._lock:
            return self._entries.get(entry_id)

    def list(self) -> List[DeadLetter]:
        with self._lock:
            return list(self._entries.values())

    def remove(self, entry_id: int) -> Optional[DeadLetter]:
        with self._lock:
            entry = self._entries.pop(entry_id, None)
            size = len(self._entries)
        if entry is not None:
            metrics.gauge_set("dlq.size", size)
        return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    async def replay(self, bus, entry_id: Optional[int] = None) -> int:
        """Republish quarantined message(s) to their ORIGINAL subject —
        with the stream still capturing it, a replayed message re-enters
        the durable flow with a fresh delivery budget. Entries are removed
        only after the publish succeeds. Returns the replay count."""
        targets = ([e for e in (self.get(entry_id),) if e is not None]
                   if entry_id is not None else self.list())
        replayed = 0
        for entry in targets:
            headers = {k: v for k, v in entry.headers.items()
                       if not k.startswith("X-Symbiont-DLQ")}
            headers[REPLAY_HEADER] = "1"
            await bus.publish(entry.subject, entry.data, headers=headers)
            self.remove(entry.id)
            metrics.inc("dlq.replayed", labels={"subject": entry.subject})
            replayed += 1
        return replayed
