"""Generation-session journal — the durability plane's write-ahead log.

Each generator role appends one self-contained JSONL snapshot per decode
chunk (engine/lm.py, at the chunk boundary's EXISTING device→host sync —
journaling adds no device syncs) to `<dir>/<role>.genlog`. The LAST record
per task is the full resume state: prompt token ids, sampling params, PRNG
key state, generated-so-far ids, and the stream's next SSE seq. When the
process supervisor declares the role dead (exit, hang verdict, or drain
deadline SIGKILL), it scans the file, rotates it aside, and republishes the
live tails as tasks.generation.resume — a surviving replica re-prefills the
prompt+generated prefix and continues the stream token-identically
(docs/RESILIENCE.md "Durable generation sessions").

Failure stance: a journal write error DISABLES the journal for this process
(counted gen.journal_errors, warned once) and generation continues — the
store being down degrades to today's lose-the-stream-on-kill behavior, it
never takes the decode path down with it.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Dict, Optional

from symbiont_tpu.utils.telemetry import metrics

log = logging.getLogger(__name__)

# journal record fields (one dict per line; unknown fields tolerated):
#   task_id      str   — required; last record per task wins
#   done         bool  — terminal marker: the stream finished/cancelled here
#   tenant       str
#   stream       bool  — original task wanted chunk deltas (vs batch-only)
#   prompt_ids   [int] — EXACT post-trim prompt ids the prefill consumed
#   max_new      int   — the request's total new-token budget
#   temperature  float
#   top_k        int
#   tokens       [int] — ALL generated ids so far, incl. the latest chunk
#   chunk_start  int   — index in `tokens` where the latest chunk begins
#                        (resume re-emits exactly that chunk's text delta:
#                        duplicates are deduped by seq at the SSE hub, so a
#                        delta the client never saw is never lost)
#   text         str   — emitted text BEFORE the latest chunk's delta (lets
#                        the adopting replica reassemble the full final text
#                        without re-decoding from token 0)
#   seq          int   — the SSE seq the latest chunk's delta carries
#   key          [int] — PRNG key_data (uint32) of the stream's BASE key;
#                        None for greedy / batch-session rows
#   key_splits   int   — chunk-splits consumed on that base so far (resume
#                        re-derives the live key host-side: wrap + advance —
#                        no per-chunk key transfer rides the decode loop)
#   ts           int   — wall-clock ms (observability only)


class GenJournal:
    """Bounded append-only JSONL WAL with an in-memory tail mirror.

    Thread-safe: appends come from engine executor threads (the stream
    producer and BatchSession.step run off the event loop). Compaction is
    piggybacked on append — past max_bytes the file is rewritten keeping
    only live tasks' tail records; past max_tasks the oldest live task is
    evicted (counted)."""

    def __init__(self, path, max_bytes: int = 8 * 1024 * 1024,
                 max_tasks: int = 512, fsync: bool = False):
        self.path = Path(path)
        self.max_bytes = int(max_bytes)
        self.max_tasks = int(max_tasks)
        self.fsync = bool(fsync)
        self.enabled = True
        self._lock = threading.Lock()
        self._tails: Dict[str, dict] = {}  # live task -> last record
        self._bytes = 0
        # reload survivors from a previous incarnation of THIS role (crash
        # between supervisor scan windows); they stay until done/evicted
        existing = _read_tails(self.path)
        if existing:
            self._tails.update(existing)
            try:
                self._bytes = self.path.stat().st_size
            except OSError:
                self._bytes = 0
            log.warning("gen journal %s: %d live session(s) recovered",
                        self.path, len(existing))
        metrics.gauge_set("gen.journal_tasks", len(self._tails))
        metrics.gauge_set("gen.journal_bytes", self._bytes)

    # ------------------------------------------------------------- writes

    def append(self, record: dict) -> None:
        """Persist one chunk-boundary snapshot. Must carry task_id."""
        if not self.enabled:
            return
        task_id = record.get("task_id")
        if not task_id:
            return
        record.setdefault("ts", int(time.time() * 1000))
        with self._lock:
            try:
                self._write_line(record)
            except OSError:
                self._degrade()
                return
            self._tails[task_id] = record
            # keep insertion order ≈ recency so eviction drops the oldest
            self._tails[task_id] = self._tails.pop(task_id)
            while len(self._tails) > self.max_tasks:
                victim, _ = next(iter(self._tails.items()))
                self._tails.pop(victim)
                metrics.inc("gen.journal_evicted")
            if self._bytes > self.max_bytes:
                try:
                    self._compact()
                except OSError:
                    self._degrade()
                    return
        metrics.inc("gen.journal_appends")
        metrics.gauge_set("gen.journal_tasks", len(self._tails))
        metrics.gauge_set("gen.journal_bytes", self._bytes)

    def mark_done(self, task_id: str) -> None:
        """Terminal marker: the stream finished (or was cancelled) here —
        the task must never be resumed from this journal."""
        if not self.enabled or not task_id:
            return
        with self._lock:
            if task_id not in self._tails:
                return
            self._tails.pop(task_id, None)
            try:
                self._write_line({"task_id": task_id, "done": True})
            except OSError:
                self._degrade()
                return
        metrics.gauge_set("gen.journal_tasks", len(self._tails))
        metrics.gauge_set("gen.journal_bytes", self._bytes)

    def live_tails(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._tails)

    def __len__(self) -> int:
        with self._lock:
            return len(self._tails)

    # ------------------------------------------------------------ innards

    def _write_line(self, record: dict) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        line = json.dumps(record, separators=(",", ":")) + "\n"
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        self._bytes += len(line.encode("utf-8"))

    def _compact(self) -> None:
        """Rewrite keeping only live tails (atomic replace — a crash mid-
        compaction leaves either the old or the new file, never a torn
        one)."""
        tmp = self.path.with_suffix(self.path.suffix + ".tmp")
        size = 0
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in self._tails.values():
                line = json.dumps(rec, separators=(",", ":")) + "\n"
                f.write(line)
                size += len(line.encode("utf-8"))
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._bytes = size
        metrics.inc("gen.journal_compactions")

    def _degrade(self) -> None:
        """Journal store down ⇒ keep generating WITHOUT durability (the
        pre-journal behavior), loudly."""
        self.enabled = False
        metrics.inc("gen.journal_errors")
        log.exception("gen journal %s write failed; generation-session "
                      "durability DISABLED for this process (streams killed "
                      "from here on are lost, pre-journal behavior)",
                      self.path)

    # ----------------------------------------------------- supervisor side

    @staticmethod
    def take_orphans(path) -> Dict[str, dict]:
        """Scan a dead role's journal for live session tails and rotate the
        file aside (so the restarted role starts fresh and a later scan
        cannot double-republish). Returns {task_id: tail record}. Pure
        blocking file I/O — callers on an event loop must run it in an
        executor."""
        path = Path(path)
        tails = _read_tails(path)
        if path.exists():
            try:
                os.replace(path, path.with_suffix(path.suffix + ".orphaned"))
            except OSError:
                log.warning("gen journal %s: rotate-aside failed", path,
                            exc_info=True)
        return tails


def _read_tails(path) -> Dict[str, dict]:
    """Last record per task, done-marked tasks removed; corrupt lines (a
    torn final append from the SIGKILL itself) are skipped."""
    path = Path(path)
    tails: Dict[str, dict] = {}
    try:
        with open(path, encoding="utf-8") as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    log.warning("gen journal %s: skipping corrupt line %d",
                                path, ln)
                    continue
                task_id = rec.get("task_id")
                if not task_id:
                    continue
                if rec.get("done"):
                    tails.pop(task_id, None)
                else:
                    tails[task_id] = rec
    except OSError:
        return {}
    return tails
