"""Breaker + spill wrappers for the store backends (graceful degradation).

`utils/retry.py` retries the STARTUP connect; these wrappers own the MID-RUN
outage. Policy per the resilience plan (docs/RESILIENCE.md):

- writes: tried through the circuit breaker; on failure (or an already-open
  breaker failing fast) the batch is SPILLED to a local JSONL WAL and the
  call reports success — the bus handler acks, the ingest pipeline keeps
  flowing, nothing is lost. The next write that gets through the breaker
  (typically the half-open probe) REPLAYS the spill first, preserving rough
  arrival order. Spill survives a process restart (the file is reloaded at
  construction). Safe because both backends take idempotent writes:
  deterministic vector point ids overwrite, graph MERGE re-merges.
- reads: tried through the breaker; when it is open, an optional embedded
  fallback store serves (stale but available) results, else the caller gets
  a fast CircuitOpenError instead of a hung HTTP timeout.
- config errors (ValueError — e.g. a dim mismatch) propagate immediately
  and never count as breaker failures: retrying cannot fix them.

Wrappers are duck-typed passthroughs (`__getattr__` delegates anything not
overridden), so the engine plane and health paths see the inner surface.
"""

from __future__ import annotations

import json
import logging
import threading
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from symbiont_tpu.resilience import faults
from symbiont_tpu.resilience.breaker import CircuitBreaker, CircuitOpenError
from symbiont_tpu.utils.telemetry import metrics

log = logging.getLogger(__name__)


class _SpillJournal:
    """Append-only JSONL spill with an in-memory mirror. File-backed when a
    path is given (entries survive a crash during the outage), purely
    in-memory otherwise (tests, ephemeral deployments)."""

    def __init__(self, path: Optional[str], what: str):
        self.what = what
        self.path = Path(path) if path else None
        self._entries: List[dict] = []
        self._lock = threading.Lock()
        if self.path is not None and self.path.exists():
            with open(self.path, encoding="utf-8") as f:
                for ln, line in enumerate(f, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        self._entries.append(json.loads(line))
                    except ValueError:
                        log.warning("%s spill %s: skipping corrupt line %d",
                                    what, self.path, ln)
            if self._entries:
                log.warning("%s: %d spilled entries recovered from %s — "
                            "will replay on backend recovery",
                            what, len(self._entries), self.path)

    def append(self, entries: Sequence[dict]) -> None:
        with self._lock:
            if self.path is not None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                with open(self.path, "a", encoding="utf-8") as f:
                    for e in entries:
                        f.write(json.dumps(e) + "\n")
                    f.flush()
                    import os
                    os.fsync(f.fileno())
            self._entries.extend(entries)
        metrics.gauge_set(f"{self.what}.spill_pending", len(self._entries))

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            if self.path is not None and self.path.exists():
                self.path.unlink()
        metrics.gauge_set(f"{self.what}.spill_pending", 0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class ResilientVectorStore:
    """Vector-store surface (ensure_collection/upsert/search/count) through
    a circuit breaker, with WAL spill for writes and an optional embedded
    read fallback for searches while the breaker is open."""

    def __init__(self, inner, breaker: Optional[CircuitBreaker] = None,
                 spill_path: Optional[str] = None, fallback=None):
        self.inner = inner
        self.breaker = breaker or CircuitBreaker("vector_store")
        self.fallback = fallback
        self._spill = _SpillJournal(spill_path, "vector_store")
        self._lock = threading.RLock()

    @property
    def supports_fused(self) -> bool:
        return getattr(self.inner, "supports_fused", False)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # ------------------------------------------------------------- internal

    def _inner_upsert(self, points):
        plan = faults.active_plan()
        if plan is not None:
            plan.sync_fault("store.upsert", self.breaker.name)
        return self.inner.upsert(points)

    def _inner_upsert_rows(self, ids, rows, payloads):
        plan = faults.active_plan()
        if plan is not None:
            plan.sync_fault("store.upsert", self.breaker.name)
        if hasattr(self.inner, "upsert_rows"):
            return self.inner.upsert_rows(ids, rows, payloads)
        # backends without the tensor-frame fast path (external Qdrant):
        # hand the row views through the point-tuple surface
        return self.inner.upsert(list(zip(ids, rows, payloads)))

    def _inner_search(self, query, top_k):
        plan = faults.active_plan()
        if plan is not None:
            plan.sync_fault("store.search", self.breaker.name)
        return self.inner.search(query, top_k)

    def _replay_pending(self) -> None:
        """Push the spill through the breaker (caller holds the lock).
        Raises on failure — the caller's batch then spills behind it."""
        pending = self._spill.snapshot()
        if not pending:
            return
        points = [(e["id"], e["vector"], e["payload"]) for e in pending]
        self.breaker.call(self._inner_upsert, points, fatal=(ValueError,))
        self._spill.clear()
        metrics.inc("store.replayed_points", len(points),
                    labels={"store": self.breaker.name})
        log.info("%s: replayed %d spilled points after recovery",
                 self.breaker.name, len(points))

    # -------------------------------------------------------------- surface

    def ensure_collection(self, dim: Optional[int] = None) -> None:
        # startup path: connect_retry inside the backend already owns this
        self.inner.ensure_collection(dim)

    def upsert(self, points: Sequence[Tuple[str, Sequence[float], dict]]) -> int:
        if not points:
            return 0
        with self._lock:
            try:
                self._replay_pending()
                return self.breaker.call(self._inner_upsert, list(points),
                                         fatal=(ValueError,))
            except ValueError:
                raise  # config error: spilling it would replay forever
            except Exception as e:
                self._spill.append([
                    {"id": pid, "vector": [float(x) for x in vec],
                     "payload": payload}
                    for pid, vec, payload in points])
                metrics.inc("store.spilled_points", len(points),
                            labels={"store": self.breaker.name})
                log.warning(
                    "%s: upsert failed (%s: %s) — %d points spilled to WAL "
                    "(%d pending) for replay on recovery", self.breaker.name,
                    type(e).__name__, e, len(points), len(self._spill))
                return len(points)

    def upsert_rows(self, ids, rows, payloads=None) -> int:
        """Tensor-frame ingest under the same breaker/spill policy as
        upsert(): the packed block stays intact on the happy path and
        degrades to per-point spill entries only when the backend is down
        (the spill is JSONL — float lists are its durable format)."""
        ids = list(ids)
        if not ids:
            return 0
        payloads = ([{}] * len(ids) if payloads is None else list(payloads))
        with self._lock:
            try:
                self._replay_pending()
                return self.breaker.call(self._inner_upsert_rows, ids, rows,
                                         payloads, fatal=(ValueError,))
            except ValueError:
                raise  # config error: spilling it would replay forever
            except Exception as e:
                import numpy as np

                vec_lists = np.asarray(rows, np.float32).tolist()
                self._spill.append([
                    {"id": pid, "vector": vec, "payload": payload}
                    for pid, vec, payload in zip(ids, vec_lists, payloads)])
                metrics.inc("store.spilled_points", len(ids),
                            labels={"store": self.breaker.name})
                log.warning(
                    "%s: upsert_rows failed (%s: %s) — %d points spilled to "
                    "WAL (%d pending) for replay on recovery",
                    self.breaker.name, type(e).__name__, e, len(ids),
                    len(self._spill))
                return len(ids)

    def search(self, query: Sequence[float], top_k: int):
        try:
            return self.breaker.call(self._inner_search, query, top_k,
                                     fatal=(ValueError,))
        except CircuitOpenError:
            if self.fallback is not None:
                metrics.inc("store.fallback_searches",
                            labels={"store": self.breaker.name})
                return self.fallback.search(query, top_k)
            raise

    def count(self) -> int:
        return self.inner.count()

    def spill_pending(self) -> int:
        return len(self._spill)

    def replay_spill(self) -> int:
        """Operator surface: force a replay attempt now (also exercised by
        the chaos suite). Returns points replayed; raises if the backend is
        still down."""
        with self._lock:
            n = len(self._spill)
            self._replay_pending()
            return n


class ResilientGraphStore:
    """Graph-store surface (ensure_schema/save_tokenized/counts/close)
    through a circuit breaker with document spill."""

    def __init__(self, inner, breaker: Optional[CircuitBreaker] = None,
                 spill_path: Optional[str] = None):
        self.inner = inner
        self.breaker = breaker or CircuitBreaker("graph_store")
        self._spill = _SpillJournal(spill_path, "graph_store")
        self._lock = threading.RLock()

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _inner_save(self, msg) -> int:
        plan = faults.active_plan()
        if plan is not None:
            plan.sync_fault("graph.save", self.breaker.name)
        return self.inner.save_tokenized(msg)

    def _replay_pending(self) -> None:
        from symbiont_tpu.schema import TokenizedTextMessage, from_dict

        pending = self._spill.snapshot()
        if not pending:
            return
        for entry in pending:
            self.breaker.call(self._inner_save,
                              from_dict(TokenizedTextMessage, entry),
                              fatal=(ValueError,))
        self._spill.clear()
        metrics.inc("store.replayed_docs", len(pending),
                    labels={"store": self.breaker.name})
        log.info("%s: replayed %d spilled documents after recovery",
                 self.breaker.name, len(pending))

    def ensure_schema(self) -> None:
        self.inner.ensure_schema()

    def save_tokenized(self, msg) -> int:
        import dataclasses

        with self._lock:
            try:
                self._replay_pending()
                return self.breaker.call(self._inner_save, msg,
                                         fatal=(ValueError,))
            except ValueError:
                raise
            except Exception as e:
                self._spill.append([dataclasses.asdict(msg)])
                metrics.inc("store.spilled_docs",
                            labels={"store": self.breaker.name})
                log.warning(
                    "%s: save failed (%s: %s) — document spilled to WAL "
                    "(%d pending) for replay on recovery", self.breaker.name,
                    type(e).__name__, e, len(self._spill))
                return -1

    def counts(self):
        return self.inner.counts()

    def close(self) -> None:
        self.inner.close()

    def spill_pending(self) -> int:
        return len(self._spill)

    def replay_spill(self) -> int:
        with self._lock:
            n = len(self._spill)
            self._replay_pending()
            return n
