"""Pure-JAX model zoo.

Models are pure functions over parameter pytrees (nested dicts of jax arrays) —
no framework Module state — so they compose directly with jit/shard_map/pjit
and with the training transforms in symbiont_tpu.train.

bert     : encoder family (BERT / XLM-RoBERTa layouts) covering the embedding
           models in BASELINE.md (MiniLM, mpnet-multilingual, bge, e5) and the
           ms-marco cross-encoder
convert  : HF torch/safetensors checkpoints → parameter pytrees
gpt      : decoder LMs (GPT-2 layout + Llama/TinyLlama layout) with static-shape
           KV-cache decode
markov   : order-1 word Markov chain (reference parity:
           services/text_generator_service/src/main.rs:13-109)
"""

from symbiont_tpu.models.bert import BertConfig, bert_encode, embed_sentences

__all__ = ["BertConfig", "bert_encode", "embed_sentences"]
