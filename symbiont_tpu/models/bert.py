"""BERT-family encoder, TPU-first.

Replaces the reference's one true compute core — the candle BertModel forward +
attention-masked mean pooling inside preprocessing_service (reference:
services/preprocessing_service/src/embedding_generator.rs:198-207) — with a
pure-JAX implementation designed for the MXU:

- params are a pytree of jax arrays; the forward is a pure function, so it
  jits/shards/differentiates with no adapter layer;
- compute dtype is bfloat16 by default (MXU-native) with float32 layernorm
  statistics and pooling; in float32 mode softmax and gelu are exact (erf)
  for numerical parity with the fp32 reference (golden tests in
  tests/test_bert_numerics.py), while bf16 mode keeps softmax in bf16 and
  uses tanh-gelu — both deviations sit below the bf16 matmul noise floor
  and together are worth ~+40% embedding throughput on v5e (see _act and
  attention for per-change measurements);
- static shapes only: the engine pads to length buckets (SURVEY.md §5.7) and
  this module never branches on data;
- one config covers the checkpoint layouts in BASELINE.md: classic BERT
  (MiniLM, bge, e5, ms-marco cross-encoder) and XLM-RoBERTa
  (paraphrase-multilingual-mpnet-base-v2, the reference's default model) which
  differs only in position-id offset (= pad_token_id + 1) and vocab details.

Layout convention for weights: all linear kernels are stored [in, out] so the
forward is `x @ W + b` (HF torch Linear weights are transposed on conversion —
see symbiont_tpu.models.convert).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from symbiont_tpu.models import quant

Params = Any  # nested dict pytree


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # XLM-RoBERTa (mpnet-multilingual) offsets position ids by pad_token_id+1=2
    # and starts them past the padding index; classic BERT uses offset 0.
    # (HF: XLMRobertaEmbeddings.create_position_ids_from_input_ids.)
    position_offset: int = 0
    hidden_act: str = "gelu"
    # dtype for matmul compute; params may be stored fp32 and cast on entry.
    dtype: str = "bfloat16"
    # "xla" = einsum attention (XLA fuses); "flash" = fused pallas kernel
    # (symbiont_tpu.ops.flash_attention) — never materializes [B,NH,S,S].
    attn_impl: str = "xla"

    @staticmethod
    def from_hf(cfg: dict) -> "BertConfig":
        """Map an HF config.json dict (BertConfig/XLMRobertaConfig) to ours."""
        model_type = cfg.get("model_type", "bert")
        offset = 0
        if model_type in ("xlm-roberta", "roberta", "mpnet"):
            offset = cfg.get("pad_token_id", 1) + 1
        return BertConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            num_layers=cfg.get("num_hidden_layers", 12),
            num_heads=cfg.get("num_attention_heads", 12),
            intermediate_size=cfg.get("intermediate_size", 4 * cfg["hidden_size"]),
            max_position_embeddings=cfg.get("max_position_embeddings", 512),
            type_vocab_size=cfg.get("type_vocab_size", 2) or 1,
            layer_norm_eps=cfg.get("layer_norm_eps", 1e-12),
            position_offset=offset,
            hidden_act=cfg.get("hidden_act", "gelu"),
        )


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float) -> jax.Array:
    # fp32 statistics regardless of compute dtype — parity with the fp32
    # reference forward within bf16 matmul noise.
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (normed * scale + bias).astype(x.dtype)


def _act(name: str, compute_dtype=None):
    if name in ("gelu", "gelu_new", "gelu_python"):
        # exact (erf) gelu in f32 for checkpoint parity; tanh approximation
        # in bf16 mode, where its ~1e-3 relative error sits well below the
        # bf16 matmul quantization noise and the erf transcendental is the
        # single most expensive VPU op in the block (measured on v5e at
        # MiniLM geometry [1024, 64]: +26% emb/s from this switch alone).
        approx = compute_dtype == jnp.bfloat16
        return partial(jax.nn.gelu, approximate=approx)
    if name == "relu":
        return jax.nn.relu
    if name == "silu":
        return jax.nn.silu
    raise ValueError(f"unsupported activation {name!r}")


def attention(
    params: Params,
    x: jax.Array,  # [B, S, H]
    mask_bias: jax.Array,  # [B, 1, 1, S] additive bias (0 or -inf-ish)
    cfg: BertConfig,
) -> jax.Array:
    B, S, H = x.shape
    nh = cfg.num_heads
    hd = H // nh

    def proj(p):
        # quant.mm: plain matmul for f32/bf16 kernels, `(x @ q) * scale`
        # for int8/fp8 QuantTensors (dequant fused — narrow HBM read)
        return (quant.mm(x, p["kernel"]) + p["bias"]).reshape(B, S, nh, hd)

    q = proj(params["query"])
    k = proj(params["key"])
    v = proj(params["value"])

    if cfg.attn_impl == "flash":
        from symbiont_tpu.ops.flash_attention import flash_attention

        ctx = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), kv_bias=mask_bias[:, 0, 0, :],
        ).transpose(0, 2, 1, 3).reshape(B, S, H)
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
        if x.dtype == jnp.bfloat16:
            # softmax in bf16: the f32 round-trip would materialize the
            # [B, nh, S, S] intermediate through HBM twice at double width,
            # and bf16 matmul noise already dominates the softmax rounding
            # (measured +13% emb/s on v5e at [1024, 64]). jax.nn.softmax
            # subtracts the row max, so exp stays in range; padded lanes get
            # the large negative bias and underflow to exactly 0.
            probs = jax.nn.softmax(
                scores + mask_bias.astype(scores.dtype), axis=-1)
        else:
            # fp32 softmax for exact parity with the fp32 reference forward
            scores = scores.astype(jnp.float32) + mask_bias.astype(jnp.float32)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, H)
    out = quant.mm(ctx, params["out"]["kernel"]) + params["out"]["bias"]
    return out


def encoder_layer(params: Params, x: jax.Array, mask_bias: jax.Array, cfg: BertConfig) -> jax.Array:
    # Post-LN transformer block (classic BERT ordering).
    attn_out = attention(params["attention"], x, mask_bias, cfg)
    x = layer_norm(x + attn_out, params["attention"]["ln"]["scale"],
                   params["attention"]["ln"]["bias"], cfg.layer_norm_eps)
    h = quant.mm(x, params["mlp"]["in"]["kernel"]) + params["mlp"]["in"]["bias"]
    h = _act(cfg.hidden_act, x.dtype)(h)
    h = quant.mm(h, params["mlp"]["out"]["kernel"]) + params["mlp"]["out"]["bias"]
    x = layer_norm(x + h, params["mlp"]["ln"]["scale"], params["mlp"]["ln"]["bias"],
                   cfg.layer_norm_eps)
    return x


def embeddings(
    params: Params,
    input_ids: jax.Array,  # [B, S] int32
    attention_mask: jax.Array,  # [B, S] int32/bool
    cfg: BertConfig,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    B, S = input_ids.shape
    tok = quant.take(params["word_embeddings"], input_ids)
    if cfg.position_offset:
        # RoBERTa-style: positions count only non-pad tokens, offset past pad id.
        mask = attention_mask.astype(jnp.int32)
        positions = jnp.cumsum(mask, axis=1) * mask + cfg.position_offset - 1
        positions = jnp.clip(positions, 0, cfg.max_position_embeddings - 1)
    else:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    pos = quant.take(params["position_embeddings"], positions)
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    typ = quant.take(params["token_type_embeddings"], token_type_ids)
    x = tok + pos + typ
    x = layer_norm(x, params["ln"]["scale"], params["ln"]["bias"], cfg.layer_norm_eps)
    return x


def bert_encode(
    params: Params,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    cfg: BertConfig,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Full encoder forward → last hidden state [B, S, H] in cfg.dtype."""
    dtype = jnp.dtype(cfg.dtype)
    # shared leaf-aware cast: floating params → compute dtype, QuantTensor
    # leaves untouched (their f32 scales must not be downcast)
    params = quant.cast_params(params, dtype)
    x = embeddings(params["embeddings"], input_ids, attention_mask, cfg, token_type_ids)
    x = x.astype(dtype)
    # additive mask bias: 0 for real tokens, large negative for padding
    mask_bias = (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) * -1e9
    for layer_params in params["layers"]:
        x = encoder_layer(layer_params, x, mask_bias, cfg)
    return x


def mean_pool(hidden: jax.Array, attention_mask: jax.Array) -> jax.Array:
    """Attention-masked mean pooling, fp32 accumulation.

    Exact semantics of the reference's pooling math (reference:
    services/preprocessing_service/src/embedding_generator.rs:201-207):
    sum(hidden * mask) / sum(mask), per sentence.
    """
    mask = attention_mask[..., None].astype(jnp.float32)
    summed = (hidden.astype(jnp.float32) * mask).sum(axis=1)
    counts = jnp.maximum(mask.sum(axis=1), 1.0)
    return summed / counts


def cls_pool(hidden: jax.Array, attention_mask: jax.Array) -> jax.Array:
    """CLS-token pooling (bge-style checkpoints)."""
    del attention_mask
    return hidden[:, 0, :].astype(jnp.float32)


POOLERS = {"mean": mean_pool, "cls": cls_pool}


def embed_sentences(
    params: Params,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    cfg: BertConfig,
    pooling: str = "mean",
    normalize: bool = False,
) -> jax.Array:
    """Encoder forward + pooling → [B, H] float32 sentence embeddings.

    The reference does not L2-normalize (cosine distance is computed by Qdrant,
    reference: services/vector_memory_service/src/main.rs:36), so normalize
    defaults to False; e5/bge recipes can turn it on.
    """
    hidden = bert_encode(params, input_ids, attention_mask, cfg)
    pooled = POOLERS[pooling](hidden, attention_mask)
    if normalize:
        pooled = pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)
    return pooled


def cross_encoder_score(
    params: Params,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    cfg: BertConfig,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Cross-encoder relevance score [B] (ms-marco rerank head: pooler + linear).

    BASELINE.md config #4: ms-marco-MiniLM-L-6 rerank on top-k search hits.
    """
    hidden = bert_encode(params, input_ids, attention_mask, cfg, token_type_ids)
    # HF BertPooler: tanh(W @ h_cls + b), then classifier [H, num_labels=1].
    cls = hidden[:, 0, :]
    pooled = jnp.tanh(quant.mm(cls, params["pooler"]["kernel"])
                      + params["pooler"]["bias"])
    logits = (quant.mm(pooled, params["classifier"]["kernel"])
              + params["classifier"]["bias"])
    return logits[..., 0].astype(jnp.float32)


# ---------------------------------------------------------------------------
# Init (random params for tests/benchmarks; real weights come from convert.py)
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: BertConfig, with_pooler: bool = False) -> Params:
    """Random init with BERT's trunc-normal(0.02) scheme; fp32 storage."""
    k_iter = iter(jax.random.split(key, 6 + cfg.num_layers * 16))

    def dense(shape):
        return jax.random.truncated_normal(next(k_iter), -2, 2, shape, jnp.float32) * 0.02

    def linear(n_in, n_out):
        return {"kernel": dense((n_in, n_out)), "bias": jnp.zeros((n_out,), jnp.float32)}

    def ln():
        return {"scale": jnp.ones((cfg.hidden_size,), jnp.float32),
                "bias": jnp.zeros((cfg.hidden_size,), jnp.float32)}

    H, I = cfg.hidden_size, cfg.intermediate_size
    params: Params = {
        "embeddings": {
            "word_embeddings": dense((cfg.vocab_size, H)),
            "position_embeddings": dense((cfg.max_position_embeddings, H)),
            "token_type_embeddings": dense((cfg.type_vocab_size, H)),
            "ln": ln(),
        },
        "layers": [
            {
                "attention": {
                    "query": linear(H, H),
                    "key": linear(H, H),
                    "value": linear(H, H),
                    "out": linear(H, H),
                    "ln": ln(),
                },
                "mlp": {"in": linear(H, I), "out": linear(I, H), "ln": ln()},
            }
            for _ in range(cfg.num_layers)
        ],
    }
    if with_pooler:
        params["pooler"] = linear(H, H)
        params["classifier"] = linear(H, 1)
    return params
