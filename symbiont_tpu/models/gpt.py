"""Decoder LMs for TPU generation: GPT-2 layout and Llama/TinyLlama layout.

The reference's "text generator" is an order-1 Markov chain trained on one
hardcoded sentence (reference: services/text_generator_service/src/main.rs:13-109,
corpus at :170). BASELINE.json's north star upgrades this to a real
autoregressive LM decoded on TPU (config #5: TinyLlama-1.1B / GPT-2,
tokens/sec/chip + time-to-first-token). This module is that LM:

- pure function over a params pytree, one config for both layouts
  (GPT-2: learned positions + LN + gelu fused-qkv; Llama: RoPE + RMSNorm +
  SwiGLU + GQA);
- static-shape KV cache: prefill at a bucketed prompt length, then a
  `lax.scan` decode loop over a fixed max_new_tokens — no data-dependent
  Python control flow, one executable per (prompt_bucket, gen_bucket);
- sampling: greedy / temperature / top-k, all shape-static;
- tensor-parallel ready: attention heads and MLP hidden are the natural shard
  axes; symbiont_tpu.parallel.sharding places them on the 'tensor' mesh axis.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from symbiont_tpu.kv import paged as _paged
from symbiont_tpu.kv.paged import PagedKVCache
from symbiont_tpu.models import quant

Params = Any


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA (llama); None → num_heads
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    arch: str = "gpt2"  # "gpt2" | "llama"
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = True
    dtype: str = "bfloat16"
    # "flash": prefill (S>1 against an EMPTY cache — generate()/train both
    # qualify) runs the fused pallas kernel over the fresh K/V; decode steps
    # (S==1) stay on the XLA cache-read path either way.
    attn_impl: str = "xla"
    # KV-cache storage: "none" = cfg.dtype slabs (the default), "int8" =
    # per-(position, head)-scaled int8 with quantize-on-append /
    # dequant-on-attend inside the decode step (models/quant.py). Part of
    # the frozen config so the cache layout keys the compiled executables.
    kv_quant: str = "none"

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def from_hf(cfg: dict) -> "GPTConfig":
        mt = cfg.get("model_type", "gpt2")
        if mt == "gpt2":
            return GPTConfig(
                vocab_size=cfg["vocab_size"],
                hidden_size=cfg.get("n_embd", 768),
                num_layers=cfg.get("n_layer", 12),
                num_heads=cfg.get("n_head", 12),
                intermediate_size=cfg.get("n_inner") or 4 * cfg.get("n_embd", 768),
                max_position_embeddings=cfg.get("n_positions", 1024),
                layer_norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
                arch="gpt2",
            )
        if mt in ("llama", "mistral"):
            return GPTConfig(
                vocab_size=cfg["vocab_size"],
                hidden_size=cfg["hidden_size"],
                num_layers=cfg["num_hidden_layers"],
                num_heads=cfg["num_attention_heads"],
                num_kv_heads=cfg.get("num_key_value_heads"),
                intermediate_size=cfg["intermediate_size"],
                max_position_embeddings=cfg.get("max_position_embeddings", 2048),
                layer_norm_eps=cfg.get("rms_norm_eps", 1e-5),
                arch="llama",
                rope_theta=cfg.get("rope_theta", 10000.0),
                tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            )
        raise ValueError(f"unsupported model_type {mt!r}")


class KVCache(NamedTuple):
    """Static-shape per-layer cache: k/v [L, B, max_len, kv_heads, head_dim]."""

    k: jax.Array
    v: jax.Array
    length: jax.Array  # [] int32 — number of valid positions


class QuantKVCache(NamedTuple):
    """int8 variant (cfg.kv_quant == "int8"): k/v slabs are int8 with one
    f32 scale per (layer, batch, position, kv_head) — quantize-on-append,
    dequant-on-attend. ~2× more session rows per HBM byte vs bf16 slabs
    (~4× vs f32) at ≤0.4% per-vector rounding; the greedy-identity gate in
    tests/test_quantization.py pins the decode-quality contract. Same field
    layout conventions as KVCache (batch at axis 1, scalar length last) so
    merge_rows and the donation-carrying decode loops treat both shapes
    uniformly."""

    k: jax.Array        # int8 [L, B, T, kv_heads, head_dim]
    v: jax.Array
    k_scale: jax.Array  # f32 [L, B, T, kv_heads]
    v_scale: jax.Array
    length: jax.Array   # [] int32


def init_cache(cfg: GPTConfig, batch: int, max_len: int, dtype):
    shape = (cfg.num_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    if cfg.kv_quant == "int8":
        sshape = shape[:-1]
        return QuantKVCache(
            jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
            jnp.zeros(sshape, jnp.float32), jnp.zeros(sshape, jnp.float32),
            jnp.zeros((), jnp.int32))
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                   jnp.zeros((), jnp.int32))


def cache_bytes(cache) -> int:
    """At-rest bytes of one cache (slabs + scale planes) — feeds the
    dtype-adjusted `lm.kv_cache_bytes` gauge in engine/lm.py."""
    return sum(int(leaf.nbytes) for leaf in cache
               if hasattr(leaf, "nbytes") and getattr(leaf, "ndim", 0) > 0)


# ---------------------------------------------------------------------------
# Norms / RoPE
# ---------------------------------------------------------------------------


def _ln(x, p, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return (((xf - mean) * jax.lax.rsqrt(var + eps)) * p["scale"] + p["bias"]).astype(x.dtype)


def _rmsnorm(x, p, eps):
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt((xf * xf).mean(-1, keepdims=True) + eps)
    return (xf * scale * p["scale"]).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x: [B, S, H, D], positions: [B, S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


def _attn(
    layer: Params,
    x: jax.Array,  # [B, S, H]
    layer_idx: int,
    cache: KVCache,
    positions: jax.Array,  # [B, S] logical positions (RoPE / wpe)
    cfg: GPTConfig,
    kv_valid: Optional[jax.Array],  # [B, T] True where a cache slot is real
) -> tuple[jax.Array, KVCache]:
    B, S, H = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim

    # Deliberately THREE projections, not a fused [H, (nh+2nkv)·hd] matmul:
    # fused qkv won an isolated microbenchmark (+23%) but LOST in the real
    # decode loop on v5e (batch-64 GPT-2: ~19.8k → ~15.6k tok/s, measured
    # with the fusion both in-body and pre-computed outside the scan) — the
    # post-matmul slicing into q/k/v interacts badly with the cache-write /
    # attention layout. Re-test on new hardware before "optimizing" this.
    q = (quant.mm(x, layer["q"]["kernel"])
         + layer["q"].get("bias", 0)).reshape(B, S, nh, hd)
    k = (quant.mm(x, layer["k"]["kernel"])
         + layer["k"].get("bias", 0)).reshape(B, S, nkv, hd)
    v = (quant.mm(x, layer["v"]["kernel"])
         + layer["v"].get("bias", 0)).reshape(B, S, nkv, hd)

    if cfg.arch == "llama":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

    # write into the static cache at [length : length+S] with ONE
    # dynamic_update_slice on the stacked [L, B, T, h, d] array. The previous
    # slice-modify-set form (cache.k[layer_idx] → DUS → .at[layer_idx].set)
    # round-tripped a full layer slab per layer per step and XLA did not
    # always fuse it away: decode ms/step grew linearly with cache length
    # (measured on v5e, TinyLlama geometry: +2.9 ms/step from T=192 → 576).
    start = cache.length

    def _dus(slab, update, rank5=True):
        idx = (layer_idx, 0, start, 0, 0) if rank5 else (layer_idx, 0, start, 0)
        return jax.lax.dynamic_update_slice(slab, update[None], idx)

    if isinstance(cache, PagedKVCache):
        # third layout (kv/paged.py): scatter the S fresh tokens through the
        # row's page-table into the flattened pool token axis, then gather
        # the row's WHOLE cache-index space [0, T) back out — element for
        # element the [B, T, kvh, hd] tensor the dense path reads, so the
        # masks / einsums / softmax below are shared verbatim and paged
        # decode stays token-identical to dense (tests/test_kv_paged.py).
        # Rows with nothing mapped at a block (padding rows, freed rows)
        # write to and read from the scratch page; those reads are always
        # masked (causality / kv_valid / discarded padding-row outputs) and
        # land on finite values, so masked probabilities stay exactly 0.0.
        assert kv_valid is not None, "paged attention requires kv_valid"
        page = cache.page_tokens
        flat_w = _paged.flat_slot_index(
            cache.page_table, start + jnp.arange(S, dtype=jnp.int32), page)

        def _tok(pool):  # [L, n_pages, page, ...] → [L, n_pages·page, ...]
            return pool.reshape((pool.shape[0], -1) + pool.shape[3:])

        def _scat(pool, vals):
            return _tok(pool).at[layer_idx, flat_w].set(
                vals.astype(pool.dtype)).reshape(pool.shape)

        T_r = kv_valid.shape[1]
        flat_r = _paged.flat_slot_index(
            cache.page_table, jnp.arange(T_r, dtype=jnp.int32), page)
        if cache.quantized:
            k_q, k_s = quant.kv_channel_quantize(k)
            v_q, v_s = quant.kv_channel_quantize(v)
            new_cache = PagedKVCache(
                _scat(cache.k, k_q), _scat(cache.v, v_q),
                _scat(cache.k_scale, k_s), _scat(cache.v_scale, v_s),
                cache.page_table, cache.length)
            k_all = quant.kv_dequantize(
                jnp.take(_tok(new_cache.k)[layer_idx], flat_r, axis=0),
                jnp.take(_tok(new_cache.k_scale)[layer_idx], flat_r, axis=0),
                x.dtype)
            v_all = quant.kv_dequantize(
                jnp.take(_tok(new_cache.v)[layer_idx], flat_r, axis=0),
                jnp.take(_tok(new_cache.v_scale)[layer_idx], flat_r, axis=0),
                x.dtype)
        else:
            new_cache = PagedKVCache(
                _scat(cache.k, k), _scat(cache.v, v),
                cache.k_scale, cache.v_scale,
                cache.page_table, cache.length)
            k_all = jnp.take(_tok(new_cache.k)[layer_idx], flat_r, axis=0)
            v_all = jnp.take(_tok(new_cache.v)[layer_idx], flat_r, axis=0)
    elif isinstance(cache, QuantKVCache):
        # quantize-on-append: each fresh (position, head) K/V vector gets
        # its own int8 scale; dequant-on-attend reads the int8 slab + the
        # head_dim×-smaller scale plane out of HBM and upcasts in registers
        k_q, k_s = quant.kv_channel_quantize(k)
        v_q, v_s = quant.kv_channel_quantize(v)
        new_cache = QuantKVCache(
            _dus(cache.k, k_q), _dus(cache.v, v_q),
            _dus(cache.k_scale, k_s, rank5=False),
            _dus(cache.v_scale, v_s, rank5=False), cache.length)
        k_all = quant.kv_dequantize(new_cache.k[layer_idx],
                                    new_cache.k_scale[layer_idx], x.dtype)
        v_all = quant.kv_dequantize(new_cache.v[layer_idx],
                                    new_cache.v_scale[layer_idx], x.dtype)
    else:
        new_cache = KVCache(_dus(cache.k, k.astype(cache.k.dtype)),
                            _dus(cache.v, v.astype(cache.v.dtype)),
                            cache.length)
        k_all = new_cache.k[layer_idx]
        v_all = new_cache.v[layer_idx]

    if cfg.attn_impl == "flash" and S > 1:
        # Prefill-from-empty: attention over exactly the S fresh tokens (the
        # cache holds nothing older — see forward()'s docstring contract), so
        # the kernel runs on the just-projected K/V, GQA handled inside.
        from symbiont_tpu.ops.flash_attention import flash_attention

        bias = None
        if kv_valid is not None:
            bias = jnp.where(kv_valid[:, :S], 0.0, -1e9).astype(jnp.float32)
        ctx = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), kv_bias=bias, causal=True,
        ).transpose(0, 2, 1, 3).reshape(B, S, H)
        out = quant.mm(ctx, layer["o"]["kernel"]) + layer["o"].get("bias", 0)
        return out, new_cache

    T = k_all.shape[1]
    # GQA without jnp.repeat: query heads are grouped onto their kv head in
    # a 5D einsum instead of materializing K/V at full head count — at
    # TinyLlama geometry (32/4 heads) the repeat inflated per-step K/V
    # traffic 8×, and it grew linearly with cache length.
    group = nh // nkv
    q5 = q.reshape(B, S, nkv, group, hd)
    scores = jnp.einsum("bsngd,btnd->bngst", q5,
                        k_all.astype(q.dtype)) / math.sqrt(hd)
    # causality runs over CACHE indices (where K/V physically live), not
    # logical positions — they differ for padded rows; padding slots are
    # excluded via kv_valid. Shapes broadcast over [B, nkv, group, S, T].
    kv_pos = jnp.arange(T)[None, None, None, None, :]
    q_cache_pos = (start + jnp.arange(S))[None, None, None, :, None]
    valid = (kv_pos <= q_cache_pos) & (kv_pos < (start + S))
    if kv_valid is not None:
        valid = valid & kv_valid[:, None, None, None, :]
    if x.dtype == jnp.bfloat16:
        # softmax in bf16, same rationale as models/bert.py attention: the
        # f32 round-trip doubles the [B, nh, S, T] intermediate's HBM
        # traffic, and bf16 matmul noise already dominates the rounding
        scores = jnp.where(valid, scores, jnp.asarray(-1e9, scores.dtype))
        probs = jax.nn.softmax(scores, axis=-1)
    else:
        scores = jnp.where(valid, scores.astype(jnp.float32), -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bngst,btnd->bsngd", probs,
                     v_all.astype(x.dtype)).reshape(B, S, H)
    out = quant.mm(ctx, layer["o"]["kernel"]) + layer["o"].get("bias", 0)
    return out, new_cache


def _block(layer, x, layer_idx, cache, positions, cfg, kv_valid):
    if cfg.arch == "gpt2":
        a, cache = _attn(layer, _ln(x, layer["ln1"], cfg.layer_norm_eps),
                         layer_idx, cache, positions, cfg, kv_valid)
        x = x + a
        h = _ln(x, layer["ln2"], cfg.layer_norm_eps)
        h = quant.mm(h, layer["mlp"]["in"]["kernel"]) + layer["mlp"]["in"]["bias"]
        h = jax.nn.gelu(h, approximate=True)  # GPT-2 uses gelu_new
        h = quant.mm(h, layer["mlp"]["out"]["kernel"]) + layer["mlp"]["out"]["bias"]
        return x + h, cache
    # llama
    a, cache = _attn(layer, _rmsnorm(x, layer["ln1"], cfg.layer_norm_eps),
                     layer_idx, cache, positions, cfg, kv_valid)
    x = x + a
    h = _rmsnorm(x, layer["ln2"], cfg.layer_norm_eps)
    gate = jax.nn.silu(quant.mm(h, layer["mlp"]["gate"]["kernel"]))
    up = quant.mm(h, layer["mlp"]["up"]["kernel"])
    h = quant.mm(gate * up, layer["mlp"]["down"]["kernel"])
    return x + h, cache


def qkv_proj(layer, h: jax.Array, positions: jax.Array, cfg: GPTConfig):
    """QKV projection + RoPE, no cache — the shared front half of attention
    for the training-side forwards (parallel/context.py, parallel/pipeline.py).
    Returns (q [B,S,nh,hd], k [B,S,nkv,hd], v [B,S,nkv,hd])."""
    B, S, _ = h.shape
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    q = (h @ layer["q"]["kernel"] + layer["q"].get("bias", 0)).reshape(B, S, nh, hd)
    k = (h @ layer["k"]["kernel"] + layer["k"].get("bias", 0)).reshape(B, S, nkv, hd)
    v = (h @ layer["v"]["kernel"] + layer["v"].get("bias", 0)).reshape(B, S, nkv, hd)
    if cfg.arch == "llama":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def block_nocache(layer, x: jax.Array, cfg: GPTConfig, attn) -> jax.Array:
    """Decoder-block scaffolding (norms, residuals, MLP) with a pluggable
    attention callable `attn(normed_hidden) -> attention output incl. o-proj`.
    ONE home for the per-arch block math on the cache-free training paths —
    _block above is its cache-threading twin for decode. Used by the
    sequence-parallel (parallel/context.py) and pipeline-parallel
    (parallel/pipeline.py) forwards so they cannot drift from each other."""
    if cfg.arch == "gpt2":
        x = x + attn(_ln(x, layer["ln1"], cfg.layer_norm_eps))
        h = _ln(x, layer["ln2"], cfg.layer_norm_eps)
        h = h @ layer["mlp"]["in"]["kernel"] + layer["mlp"]["in"]["bias"]
        h = jax.nn.gelu(h, approximate=True)  # GPT-2 uses gelu_new
        h = h @ layer["mlp"]["out"]["kernel"] + layer["mlp"]["out"]["bias"]
        return x + h
    x = x + attn(_rmsnorm(x, layer["ln1"], cfg.layer_norm_eps))
    h = _rmsnorm(x, layer["ln2"], cfg.layer_norm_eps)
    gate = jax.nn.silu(h @ layer["mlp"]["gate"]["kernel"])
    up = h @ layer["mlp"]["up"]["kernel"]
    h = (gate * up) @ layer["mlp"]["down"]["kernel"]
    return x + h


def forward(
    params: Params,
    input_ids: jax.Array,  # [B, S]
    cache: KVCache,
    positions: jax.Array,  # [B, S] absolute logical positions of these tokens
    cfg: GPTConfig,
    kv_valid: Optional[jax.Array] = None,  # [B, cache_len] mask of real slots
) -> tuple[jax.Array, KVCache]:
    """Forward over S new tokens against the cache → (logits [B, S, V], cache).

    Tokens are written at cache indices [cache.length, cache.length+S); when
    rows carry left-padding (batched generation), pass kv_valid=False on the
    padding slots so attention never reads them.

    With cfg.attn_impl == "flash", any S>1 call MUST be prefill against an
    empty cache (cache.length == 0) — the fused kernel attends over exactly
    the S fresh tokens and would silently ignore older cache entries.
    generate() and the trainer both satisfy this; chunked prefill against a
    partially-filled cache requires attn_impl == "xla"."""
    dtype = jnp.dtype(cfg.dtype)
    # leaf-aware cast (models/quant.py): floating params → compute dtype,
    # QuantTensor leaves untouched so their f32 scales survive
    params = quant.cast_params(params, dtype)
    x = quant.take(params["wte"], input_ids)
    if cfg.arch == "gpt2":
        x = x + quant.take(params["wpe"], positions)
    x = x.astype(dtype)  # quantized gathers dequantize to f32
    for i, layer in enumerate(params["layers"]):
        x, cache = _block(layer, x, i, cache, positions, cfg, kv_valid)
    if cfg.arch == "gpt2":
        x = _ln(x, params["ln_f"], cfg.layer_norm_eps)
    else:
        x = _rmsnorm(x, params["ln_f"], cfg.layer_norm_eps)
    if cfg.tie_word_embeddings:
        logits = quant.mm_tied(x, params["wte"]).astype(jnp.float32)
    else:
        logits = quant.mm(x, params["lm_head"]["kernel"]).astype(jnp.float32)
    return logits, cache


# ---------------------------------------------------------------------------
# Generation (static shapes; one executable per (prompt_len, max_new) pair)
# ---------------------------------------------------------------------------


def _top_k_bucket(top_k: int, vocab: int) -> int:
    """Static power-of-two bucket for the top-k cutoff. lax.top_k needs a
    static k, but compiling one executable per client-supplied value would
    mint unbounded executables (the ills bucketing exists to prevent
    everywhere else in this repo) — so the compiled cutoff width is the next
    power of two and the *exact* requested k selects the threshold
    dynamically inside it (_sample). 0 = no cutoff (top_k<=0, or >= vocab
    where the cutoff is a no-op)."""
    if top_k <= 0 or top_k >= vocab:
        return 0
    b = 8
    while b < top_k:
        b *= 2
    return min(b, vocab)


def _norm_sampling(temperature, top_k, B: int, vocab: int):
    """Normalize scalar-or-per-row sampling params to [B] device vectors plus
    the static top-k bucket wide enough for every row's cutoff."""
    t = np.broadcast_to(np.asarray(temperature, np.float32), (B,))
    k = np.broadcast_to(np.asarray(top_k, np.int32), (B,))
    cut = [int(x) for x in k if 0 < int(x) < vocab]
    bucket = _top_k_bucket(max(cut), vocab) if cut else 0
    return jnp.asarray(t), jnp.asarray(k), bucket


def _sample(logits: jax.Array, key: jax.Array, temperature, top_k,
            top_k_bucket: int) -> jax.Array:
    """temperature/top_k are TRACED per-row [B] vectors (a new sampling value
    must not recompile the decode loop, and rows of one batch may carry
    different sampling params); only top_k_bucket is static. Per row:
    temperature<=0 selects greedy; top_k<=0 (or >= vocab) disables the
    cutoff; otherwise semantics match exact top-k for any k in the bucket."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.asarray(temperature, jnp.float32)
    scaled = logits / jnp.maximum(t, 1e-6)[..., None]
    tk = jnp.asarray(top_k, jnp.int32)
    if top_k_bucket > 0:
        vals = jax.lax.top_k(scaled, top_k_bucket)[0]  # [..., bucket] desc
        idx = jnp.clip(tk, 1, top_k_bucket) - 1
        kth = jnp.take_along_axis(vals, idx[..., None], axis=-1)  # exact k-th
        cut = (tk > 0) & (tk < vocab)
        scaled = jnp.where(cut[..., None] & (scaled < kth), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(t <= 0.0, greedy, sampled)


def _align_prompt(prompt_ids: jax.Array, prompt_mask: jax.Array,
                  max_new_tokens: int):
    """Right-align prefix-aligned prompts (shared by generate and the
    streaming decoder): returns (ids_r, positions, kv_valid, prompt_len)."""
    B, P = prompt_ids.shape
    prompt_len = prompt_mask.astype(jnp.int32).sum(axis=1)  # [B]
    pad = P - prompt_len  # left-pad width per row after alignment

    j = jnp.arange(P, dtype=jnp.int32)[None, :]
    src = j - pad[:, None]
    ids_r = jnp.take_along_axis(prompt_ids, jnp.clip(src, 0, P - 1), axis=1)
    ids_r = jnp.where(src >= 0, ids_r, 0)
    positions = jnp.maximum(src, 0)

    kv_valid = jnp.concatenate(
        [j >= pad[:, None], jnp.ones((B, max_new_tokens), bool)], axis=1)
    return ids_r, positions, kv_valid, prompt_len


def _decode_step(params, cfg: GPTConfig, kv_valid, temperature, top_k,
                 top_k_bucket: int, eos_id: int):
    """The one-token decode step shared by the full scan and chunked scans."""

    def step(carry, step_key):
        cache, cur_logits, cur_pos, done = carry
        tok = _sample(cur_logits, step_key, temperature, top_k, top_k_bucket)
        tok = jnp.where(done, 0, tok)
        if eos_id >= 0:
            counted = ~done & (tok != eos_id)
            new_done = done | (tok == eos_id)
        else:
            counted = ~done
            new_done = done
        logits, new_cache = forward(params, tok[:, None], cache,
                                    cur_pos[:, None], cfg, kv_valid)
        new_cache = new_cache._replace(length=cache.length + 1)
        return (new_cache, logits[:, 0, :], cur_pos + 1, new_done), (tok, counted)

    return step


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens"))
def prefill(params, prompt_ids, prompt_mask, cfg: GPTConfig,
            max_new_tokens: int):
    """Prompt forward against a fresh cache sized for max_new_tokens more
    tokens. Returns (cache, next_logits, kv_valid, prompt_len) — the carry a
    chunked decode loop resumes from."""
    B, P = prompt_ids.shape
    cache = init_cache(cfg, B, P + max_new_tokens, jnp.dtype(cfg.dtype))
    ids_r, positions, kv_valid, prompt_len = _align_prompt(
        prompt_ids, prompt_mask, max_new_tokens)
    logits, cache = forward(params, ids_r, cache, positions, cfg, kv_valid)
    cache = cache._replace(length=jnp.asarray(P, jnp.int32))
    return cache, logits[:, -1, :], kv_valid, prompt_len


@partial(jax.jit, static_argnames=("cfg", "top_k_bucket", "eos_id"),
         donate_argnames=("cache", "cur_logits", "cur_pos", "done"))
def _decode_chunk_jit(params, cache, cur_logits, cur_pos, done, kv_valid,
                      keys, temperature, top_k, cfg: GPTConfig,
                      top_k_bucket: int, eos_id: int):
    # The carry is DONATED: the KV cache at serving size is GBs (TinyLlama
    # b128 x 960 slots = 5.5 GB), and without donation every chunk call kept
    # input AND output caches resident and copied between them — measured
    # 385 ms/step at that shape (HBM thrash) vs ~14 ms donated. Callers
    # must treat the passed-in carry as consumed (every call site
    # reassigns).
    step = _decode_step(params, cfg, kv_valid, temperature, top_k,
                        top_k_bucket, eos_id)
    (cache, logits, pos, done), (tokens, counted) = jax.lax.scan(
        step, (cache, cur_logits, cur_pos, done), keys)
    return cache, logits, pos, done, tokens.T, counted.T


def decode_chunk(params, cache, cur_logits, cur_pos, done, kv_valid, keys,
                 cfg: GPTConfig, temperature=0.8, top_k=40,
                 eos_id: int = -1):
    """Scan `len(keys)` decode steps from a carried state; chunk length is
    static via the keys shape, so a streaming loop reuses ONE executable per
    (prompt_bucket, chunk) pair — temperature and the exact top_k are traced
    per-row vectors (only the power-of-two top_k bucket is compiled in), so
    new sampling values reuse it too. Returns (carry..., tokens [B, C],
    counted [B, C])."""
    t, k, bucket = _norm_sampling(temperature, top_k,
                                  cur_logits.shape[0], cfg.vocab_size)
    return _decode_chunk_jit(
        params, cache, cur_logits, cur_pos, done, kv_valid, keys,
        t, k, cfg, top_k_bucket=bucket, eos_id=eos_id)


def merge_rows(cache_a, logits_a, pos_a, done_a, kv_valid_a,
               cache_b, logits_b, pos_b, done_b, kv_valid_b,
               row_map, prompt_width: int):
    """Continuous batching: splice freshly-prefilled rows (state b) into an
    in-flight chunked decode (state a) at a chunk boundary. cache_a is
    DONATED (serving-size caches are GBs; the input is dead after the
    splice — every caller reassigns from the return). cache_b cannot alias
    the output (its batch dim is the admission bucket, not the session's),
    so donating it would only provoke unusable-donation warnings.

    row_map [B] int32: row_map[i] = j ≥ 0 replaces a's row i with b's row j;
    -1 keeps a's row. Both states must share the cache layout (same
    prompt_width bucket and new-token bucket, so T matches). The spliced
    rows' cache slots [prompt_width, a.length) — the steps a decoded before
    admission — are masked invalid: the row's own decode continues at cache
    slot a.length while its logical position carries on from its prompt, so
    its output is EXACTLY what a standalone decode would produce (the same
    right-alignment independence generate() guarantees across batchmates).

    Three layouts splice through here. Dense KVCache and int8 QuantKVCache
    share the field-wise jit below (scale planes ride batch axis 1 like the
    slabs). For the paged layout cache_a is a PagedKVCache and cache_b is a
    TRIPLE ``(staging, scatter_table, new_page_table)``: the dense-staged
    prefill (None when every admitted row was a full radix hit and prefill
    was skipped outright), a [bb, prompt_width/page] table mapping each
    staging row's prompt blocks to the pool pages the engine allocated for
    it (all-scratch rows for rejected / full-hit staging rows), and the
    session's rebuilt [B, n_blocks] device page table. The cache half then
    happens IN THE POOL (kv/paged.scatter_prompt, pools donated) while the
    row-state half (kv/paged.merge_row_state) applies the same row_map +
    gap-masking contract as the dense splice.

    One compiled executable per (shapes, prompt_width); the row pattern is
    traced, so which rows get replaced never recompiles."""
    if isinstance(cache_a, PagedKVCache):
        staging, scatter_table, new_page_table = cache_b
        k, v, ks, vs = cache_a.k, cache_a.v, cache_a.k_scale, cache_a.v_scale
        if staging is not None:
            k, v, ks, vs = _paged.scatter_prompt(
                k, v, ks, vs, staging, scatter_table, prompt_width)
        logits, pos, done, kvv = _paged.merge_row_state(
            logits_a, pos_a, done_a, kv_valid_a,
            logits_b, pos_b, done_b, kv_valid_b,
            row_map, cache_a.length, prompt_width)
        cache = PagedKVCache(k, v, ks, vs, new_page_table, cache_a.length)
        return cache, logits, pos, done, kvv
    return _merge_rows_jit(cache_a, logits_a, pos_a, done_a, kv_valid_a,
                           cache_b, logits_b, pos_b, done_b, kv_valid_b,
                           row_map, prompt_width=prompt_width)


@partial(jax.jit, static_argnames=("prompt_width",),
         donate_argnames=("cache_a",))
def _merge_rows_jit(cache_a, logits_a, pos_a, done_a, kv_valid_a,
                    cache_b, logits_b, pos_b, done_b, kv_valid_b,
                    row_map, prompt_width: int):
    B = logits_a.shape[0]
    T = cache_a.k.shape[2]
    sel = row_map >= 0
    j = jnp.clip(row_map, 0, logits_b.shape[0] - 1)

    def pick(a, b, batch_axis=0):
        take = jnp.take(b, j, axis=batch_axis)
        shape = [1] * a.ndim
        shape[batch_axis] = B
        return jnp.where(sel.reshape(shape), take, a)

    # the gap a decoded while b wasn't there: invalid for spliced rows forever
    t_idx = jnp.arange(T)
    gap = (t_idx >= prompt_width) & (t_idx < cache_a.length)
    kv_b = kv_valid_b & ~gap[None, :]
    # field-wise splice covers both cache layouts (KVCache and the int8
    # QuantKVCache, whose scale planes ride batch axis 1 like the slabs);
    # the scalar `length` field keeps a's value
    cache = type(cache_a)(*[
        fa if fa.ndim == 0 else pick(fa, fb, batch_axis=1)
        for fa, fb in zip(cache_a, cache_b)])
    return (cache, pick(logits_a, logits_b), pick(pos_a, pos_b),
            pick(done_a, done_b), pick(kv_valid_a, kv_b))


# ---------------------------------------------------------------------------
# Speculative decoding (docs/SPECULATIVE.md): draft k greedy tokens on a
# small model's own cache, score all k+1 positions with ONE target forward,
# emit the longest accepted prefix plus the target's correction.
#
# State contract ("spec state", vs the "plain state" decode_chunk carries):
# the cache holds every emitted token EXCEPT the last one, which rides
# host-side as `pending` [B]; `cur_pos` is pending's logical position. Each
# round writes the S = k+1 window [pending, d_1..d_k] into BOTH caches
# (drafter via its scan + one extra forward of d_k, target via the verify
# forward), so the two planes share ONE kv_valid / cur_pos / done and one
# scalar length advance of S per round. Raggedness lives ONLY in kv_valid:
# slot j of a row's window stays valid iff j <= accepted(row) — rejected
# draft slots become permanent holes the attention mask already excludes
# (the same mechanism that masks left-padding), so plain decode_chunk keeps
# working against a hole-y cache and no attention code changes at all.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("top_k_bucket", "eos_id"))
def _spec_first_jit(cur_logits, done, key, temperature, top_k,
                    top_k_bucket: int, eos_id: int):
    """plain → spec transition: sample ONE token from carried logits (exactly
    what the next plain step would emit) without forwarding it — it becomes
    `pending`. Returns (tok, counted, new_done)."""
    tok = _sample(cur_logits, key, temperature, top_k, top_k_bucket)
    tok = jnp.where(done, 0, tok)
    if eos_id >= 0:
        counted = ~done & (tok != eos_id)
        new_done = done | (tok == eos_id)
    else:
        counted = ~done
        new_done = done
    return tok, counted, new_done


def spec_first(cur_logits, done, key, cfg: GPTConfig, temperature=0.8,
               top_k=40, eos_id: int = -1):
    t, k, bucket = _norm_sampling(temperature, top_k,
                                  cur_logits.shape[0], cfg.vocab_size)
    return _spec_first_jit(cur_logits, done, key, t, k,
                           top_k_bucket=bucket, eos_id=eos_id)


@partial(jax.jit, static_argnames=("dcfg", "spec_k"),
         donate_argnames=("d_cache",))
def _draft_chunk_jit(draft_params, d_cache, pending, cur_pos, done, kv_valid,
                     dcfg: GPTConfig, spec_k: int):
    """Drafter plane: scan k GREEDY steps from `pending` on the drafter's own
    dense cache — one dispatch, same shape discipline as decode_chunk. The
    drafter always proposes greedily (a point-mass proposal), which keeps
    sampled-row acceptance a bare p_target(draft) coin flip in verify. After
    the scan, d_k itself is forwarded once more (logits discarded) so the
    drafter consumes exactly the same k+1 window slots the target's verify
    writes — slot symmetry is what lets both planes share one kv_valid."""

    def step(carry, _):
        cache, tok, pos = carry
        tok = jnp.where(done, 0, tok)
        logits, cache = forward(draft_params, tok[:, None], cache,
                                pos[:, None], dcfg, kv_valid)
        cache = cache._replace(length=cache.length + 1)
        nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        return (cache, nxt, pos + 1), nxt

    (cache, tok, pos), drafts = jax.lax.scan(
        step, (d_cache, pending, cur_pos), None, length=spec_k)
    tok = jnp.where(done, 0, tok)
    _, cache = forward(draft_params, tok[:, None], cache, pos[:, None],
                       dcfg, kv_valid)
    cache = cache._replace(length=cache.length + 1)
    return cache, drafts.T  # [B, k]


def draft_chunk(draft_params, d_cache, pending, cur_pos, done, kv_valid,
                dcfg: GPTConfig, spec_k: int):
    return _draft_chunk_jit(draft_params, d_cache, pending, cur_pos, done,
                            kv_valid, dcfg=dcfg, spec_k=spec_k)


@partial(jax.jit, static_argnames=("cfg", "top_k_bucket", "eos_id"),
         donate_argnames=("cache", "cur_pos", "done", "kv_valid"))
def _verify_chunk_jit(params, cache, pending, drafts, cur_pos, done, kv_valid,
                      key_u, key_c, temperature, top_k, cfg: GPTConfig,
                      top_k_bucket: int, eos_id: int):
    B, k = drafts.shape
    S = k + 1
    # One forward scores every draft position: logits[:, j] is the target's
    # next-token distribution AFTER seq[:, :j+1], i.e. slot j scores d_{j+1}
    # (and slot k is the bonus position past the last draft).
    seq = jnp.concatenate([pending[:, None], drafts], axis=1)  # [B, S]
    seq = jnp.where(done[:, None], 0, seq)
    positions = cur_pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    logits, new_cache = forward(params, seq, cache, positions, cfg, kv_valid)
    new_cache = new_cache._replace(length=cache.length + S)

    # the SAME transformed distribution _sample draws from (temperature
    # scale + exact-k top-k cutoff inside the static bucket), per row
    t = jnp.asarray(temperature, jnp.float32)
    greedy_row = t <= 0.0
    tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
    scaled = logits / jnp.maximum(t, 1e-6)[:, None, None]
    tk = jnp.asarray(top_k, jnp.int32)
    if top_k_bucket > 0:
        vals = jax.lax.top_k(scaled, top_k_bucket)[0]  # [B, S, bucket] desc
        idx = jnp.clip(tk, 1, top_k_bucket) - 1
        kth = jnp.take_along_axis(
            vals, jnp.broadcast_to(idx[:, None, None], (B, S, 1)), axis=-1)
        cut = (tk > 0) & (tk < cfg.vocab_size)
        scaled = jnp.where(cut[:, None, None] & (scaled < kth),
                           -jnp.inf, scaled)

    # Acceptance. Greedy rows: longest exact-match prefix against the
    # target's own argmax — token-identical to plain decode by construction.
    # Sampled rows: the drafter's proposal is a point mass (greedy drafts),
    # so min(1, p/q) collapses to p_target(draft) — one uniform per slot.
    probs = jax.nn.softmax(scaled, axis=-1)
    p_d = jnp.take_along_axis(probs[:, :k, :], drafts[:, :, None],
                              axis=-1)[..., 0]            # [B, k]
    u = jax.random.uniform(key_u, (B, k))
    acc = jnp.where(greedy_row[:, None], drafts == tgt[:, :k], u < p_d)
    m = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)  # [B] 0..k

    # Correction token at output slot m. Sampled rows draw from the
    # rejection residual — p with the rejected draft token masked out
    # (point-mass q makes norm(max(p-q,0)) exactly that), or the untouched
    # slot-k distribution when every draft was accepted (the bonus token).
    drafts_pad = jnp.concatenate([drafts, jnp.zeros((B, 1), jnp.int32)], 1)
    scaled_m = jnp.take_along_axis(scaled, m[:, None, None], axis=1)[:, 0, :]
    d_rej = jnp.take_along_axis(drafts_pad, m[:, None], axis=1)[:, 0]
    rej_mask = jax.nn.one_hot(d_rej, cfg.vocab_size, dtype=bool)
    do_mask = (~greedy_row) & (m < k)
    scaled_m = jnp.where(do_mask[:, None] & rej_mask, -jnp.inf, scaled_m)
    sampled_c = jax.random.categorical(key_c, scaled_m, axis=-1)
    tgt_m = jnp.take_along_axis(tgt, m[:, None], axis=1)[:, 0]
    corr = jnp.where(greedy_row, tgt_m, sampled_c.astype(jnp.int32))

    # Emission: slots 0..m-1 are the accepted drafts, slot m the correction.
    # EOS bookkeeping mirrors _decode_step: the eos token itself is emitted
    # but not counted, nothing after it counts, the row goes done.
    jj = jnp.arange(S, dtype=jnp.int32)[None, :]
    out = jnp.where(jj < m[:, None], drafts_pad,
                    jnp.where(jj == m[:, None], corr[:, None], 0))
    emit = (jj <= m[:, None]) & ~done[:, None]
    out = jnp.where(emit, out, 0)
    if eos_id >= 0:
        hit = emit & (out == eos_id)
        before = jnp.cumsum(hit, axis=1) - hit  # exclusive: any eos earlier?
        counted = emit & (before == 0) & (out != eos_id)
        new_done = done | hit.any(axis=1)
    else:
        counted = emit
        new_done = done

    # Window validity + advances: rejected slots j > m become permanent
    # kv_valid holes; rows already done mark the whole window "valid" junk,
    # exactly like plain decode writing forced-0 tokens for done rows.
    m_adv = jnp.where(done, k, m)
    window = jnp.arange(S, dtype=jnp.int32)[None, :] <= m_adv[:, None]
    new_kvv = jax.lax.dynamic_update_slice(kv_valid, window, (0, cache.length))
    new_pos = cur_pos + jnp.where(done, S, m + 1)
    new_pending = jnp.where(new_done, 0, corr)
    emitted = jnp.where(done, 0, m + 1)
    return (new_cache, new_pending, new_pos, new_done, new_kvv,
            out, counted, emitted)


def verify_chunk(params, cache, pending, drafts, cur_pos, done, kv_valid,
                 key, cfg: GPTConfig, temperature=0.8, top_k=40,
                 eos_id: int = -1):
    """Score k drafts + emit in ONE target dispatch. The carry (cache,
    cur_pos, done, kv_valid) is donated like decode_chunk's — callers
    reassign from the return. Returns (cache, pending, cur_pos, done,
    kv_valid, out [B, k+1], counted [B, k+1], emitted [B]); a row's emitted
    tokens are out[i, :emitted[i]] filtered through counted (eos cut)."""
    t, tk, bucket = _norm_sampling(temperature, top_k,
                                   pending.shape[0], cfg.vocab_size)
    key_u, key_c = jax.random.split(key)
    return _verify_chunk_jit(params, cache, pending, drafts, cur_pos, done,
                             kv_valid, key_u, key_c, t, tk, cfg,
                             top_k_bucket=bucket, eos_id=eos_id)


@partial(jax.jit, static_argnames=("cfg",),
         donate_argnames=("cache", "cur_pos"))
def _ingest_pending_jit(params, cache, pending, cur_pos, done, kv_valid,
                        cfg: GPTConfig):
    tok = jnp.where(done, 0, pending)
    logits, new_cache = forward(params, tok[:, None], cache,
                                cur_pos[:, None], cfg, kv_valid)
    new_cache = new_cache._replace(length=cache.length + 1)
    return new_cache, logits[:, 0, :], cur_pos + 1


def ingest_pending(params, cache, pending, cur_pos, done, kv_valid,
                   cfg: GPTConfig):
    """spec → plain transition: forward `pending` into the cache (one slot)
    and recover carried logits, after which decode_chunk / merge_rows apply.
    The logits are what an identically-positioned plain step would compute,
    so a greedy stream stays token-identical across the mode switch."""
    return _ingest_pending_jit(params, cache, pending, cur_pos, done,
                               kv_valid, cfg=cfg)


@partial(jax.jit, donate_argnames=("cache_a",))
def merge_cache_rows(cache_a, cache_b, row_map):
    """Drafter-side half of a continuous-batching splice: field-wise row
    pick (batch axis 1 on every slab, scalar length keeps a's) mirroring
    _merge_rows_jit, minus the logits/gap handling — gap validity for the
    drafter is governed by the SHARED kv_valid the target-side merge_rows
    already masks. cache_b rows come from a drafter prefill at the same
    prompt bucket, so slabs line up slot for slot."""
    B = cache_a.k.shape[1]
    sel = row_map >= 0
    j = jnp.clip(row_map, 0, cache_b.k.shape[1] - 1)

    def pick(a, b):
        take = jnp.take(b, j, axis=1)
        shape = [1] * a.ndim
        shape[1] = B
        return jnp.where(sel.reshape(shape), take, a)

    return type(cache_a)(*[fa if fa.ndim == 0 else pick(fa, fb)
                           for fa, fb in zip(cache_a, cache_b)])


@partial(jax.jit, static_argnames=("dcfg",), donate_argnames=("d_cache",))
def _track_chunk_jit(draft_params, d_cache, toks, start_pos, kv_valid,
                     dcfg: GPTConfig):
    B, S = toks.shape
    positions = start_pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    _, cache = forward(draft_params, toks, cache=d_cache,
                       positions=positions, cfg=dcfg, kv_valid=kv_valid)
    return cache._replace(length=d_cache.length + S)


def track_chunk(draft_params, d_cache, toks, start_pos, kv_valid,
                dcfg: GPTConfig):
    """Drafter lockstep through a PLAIN interlude: teacher-force the tokens
    a plain decode chunk just wrote into the TARGET cache (decode_chunk's
    returned `toks` — exactly its written content, done-row zeros included)
    into the drafter's cache at the same slots/positions, one dispatch.
    Keeps the two planes slot-symmetric so speculation can re-enter after a
    margin fallback or a splice without a drafter re-prefill."""
    return _track_chunk_jit(draft_params, d_cache, toks, start_pos, kv_valid,
                            dcfg=dcfg)


@partial(jax.jit,
         static_argnames=("cfg", "max_new_tokens", "top_k_bucket", "eos_id"))
def _generate_jit(params, prompt_ids, prompt_mask, key, temperature, top_k,
                  cfg: GPTConfig, max_new_tokens: int, top_k_bucket: int,
                  eos_id: int):
    B = prompt_ids.shape[0]
    cache, next_logits, kv_valid, prompt_len = prefill(
        params, prompt_ids, prompt_mask, cfg, max_new_tokens)

    step = _decode_step(params, cfg, kv_valid, temperature, top_k,
                        top_k_bucket, eos_id)
    keys = jax.random.split(key, max_new_tokens)
    init = (cache, next_logits, prompt_len, jnp.zeros((B,), bool))
    _, (tokens, counted) = jax.lax.scan(step, init, keys)
    tokens = tokens.T  # [B, max_new]
    lengths = counted.T.astype(jnp.int32).sum(axis=1)
    return tokens, lengths


def generate(
    params: Params,
    prompt_ids: jax.Array,  # [B, P] left-padded with pad_id? No: right-aligned real tokens
    prompt_mask: jax.Array,  # [B, P] 1 for real prompt tokens (prefix-aligned)
    key: jax.Array,
    cfg: GPTConfig,
    max_new_tokens: int = 64,
    temperature=0.8,
    top_k=40,
    eos_id: int = -1,
) -> tuple[jax.Array, jax.Array]:
    """Prefill + scan decode. Returns (tokens [B, max_new_tokens], lengths [B]).

    Prompts arrive prefix-aligned (real tokens first, padding after); they are
    right-aligned internally so every row's last prompt token sits at cache
    index P-1 and decode steps share cache indices P, P+1, ... across the
    batch, with left-padding slots masked out of attention via kv_valid.
    Rows stop at eos_id (if ≥0); lengths counts tokens generated before eos.

    temperature and the exact top_k are traced per-row [B] vectors (scalars
    broadcast) — per-request sampling values never recompile, and rows of one
    batch may sample differently; only (shapes, cfg, top_k's power-of-two
    bucket, eos_id) key the executable.
    """
    t, k, bucket = _norm_sampling(temperature, top_k,
                                  prompt_ids.shape[0], cfg.vocab_size)
    return _generate_jit(params, prompt_ids, prompt_mask, key, t, k, cfg,
                         max_new_tokens=max_new_tokens,
                         top_k_bucket=bucket, eos_id=eos_id)


# ---------------------------------------------------------------------------
# Init (random params; real weights via convert_gpt)
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: GPTConfig) -> Params:
    keys = jax.random.split(key, 4 + cfg.num_layers)

    def dense(k, shape, scale=0.02):
        return jax.random.normal(k, shape, jnp.float32) * scale

    H, I, hd = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    nkv = cfg.kv_heads

    def make_layer(k):
        ks = jax.random.split(k, 8)
        if cfg.arch == "gpt2":
            return {
                "ln1": {"scale": jnp.ones(H), "bias": jnp.zeros(H)},
                "ln2": {"scale": jnp.ones(H), "bias": jnp.zeros(H)},
                "q": {"kernel": dense(ks[0], (H, H)), "bias": jnp.zeros(H)},
                "k": {"kernel": dense(ks[1], (H, H)), "bias": jnp.zeros(H)},
                "v": {"kernel": dense(ks[2], (H, H)), "bias": jnp.zeros(H)},
                "o": {"kernel": dense(ks[3], (H, H)), "bias": jnp.zeros(H)},
                "mlp": {
                    "in": {"kernel": dense(ks[4], (H, I)), "bias": jnp.zeros(I)},
                    "out": {"kernel": dense(ks[5], (I, H)), "bias": jnp.zeros(H)},
                },
            }
        return {
            "ln1": {"scale": jnp.ones(H)},
            "ln2": {"scale": jnp.ones(H)},
            "q": {"kernel": dense(ks[0], (H, H))},
            "k": {"kernel": dense(ks[1], (H, nkv * hd))},
            "v": {"kernel": dense(ks[2], (H, nkv * hd))},
            "o": {"kernel": dense(ks[3], (H, H))},
            "mlp": {
                "gate": {"kernel": dense(ks[4], (H, I))},
                "up": {"kernel": dense(ks[5], (H, I))},
                "down": {"kernel": dense(ks[6], (I, H))},
            },
        }

    params: Params = {
        "wte": dense(keys[0], (cfg.vocab_size, H)),
        "layers": [make_layer(k) for k in keys[4:]],
        "ln_f": ({"scale": jnp.ones(H), "bias": jnp.zeros(H)} if cfg.arch == "gpt2"
                 else {"scale": jnp.ones(H)}),
    }
    if cfg.arch == "gpt2":
        params["wpe"] = dense(keys[1], (cfg.max_position_embeddings, H))
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense(keys[2], (H, cfg.vocab_size))}
    return params
