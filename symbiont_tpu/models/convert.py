"""HF checkpoint → JAX parameter pytrees.

Replaces the reference's weight path — hf-hub download + unsafe mmap VarBuilder
into candle (reference:
services/preprocessing_service/src/embedding_generator.rs:25-58,106-124) — with
an offline converter: local safetensors / torch `.bin` state_dicts are mapped
into the pytree layout of symbiont_tpu.models.bert (and .gpt). No network: the
engine points at a local model dir (config.engine.model_dir). Converted params
can be checkpointed via symbiont_tpu.train.checkpoint so engine restarts skip
reconversion (SURVEY.md §5.4 plan).

Handles the BERT-family layouts named in BASELINE.md: bert.* (MiniLM/bge/e5,
ms-marco cross-encoder), roberta.* (xlm-roberta = mpnet-multilingual), plus
bare (headless) encoder dumps. Torch Linear stores [out, in]; kernels are
transposed to [in, out] on conversion (see bert.py layout note).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Dict

import numpy as np

from symbiont_tpu.models.bert import BertConfig

Params = Any


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    # torch tensor (cpu) without importing torch at module load
    return t.detach().cpu().numpy()


def load_state_dict(model_dir: str | Path) -> Dict[str, np.ndarray]:
    """Load weights from a local model dir: model.safetensors (preferred,
    incl. sharded index — parity with the reference's sharded handling at
    embedding_generator.rs:36-50) or pytorch_model.bin."""
    model_dir = Path(model_dir)
    st = model_dir / "model.safetensors"
    idx = model_dir / "model.safetensors.index.json"
    if st.exists():
        from safetensors.numpy import load_file

        return load_file(str(st))
    if idx.exists():
        from safetensors.numpy import load_file

        weight_map = json.loads(idx.read_text())["weight_map"]
        out: Dict[str, np.ndarray] = {}
        for shard in sorted(set(weight_map.values())):
            out.update(load_file(str(model_dir / shard)))
        return out
    bin_path = model_dir / "pytorch_model.bin"
    if bin_path.exists():
        import torch

        sd = torch.load(str(bin_path), map_location="cpu", weights_only=True)
        return {k: _to_numpy(v) for k, v in sd.items()}
    raise FileNotFoundError(f"no model.safetensors or pytorch_model.bin in {model_dir}")


def load_hf_config(model_dir: str | Path) -> dict:
    return json.loads((Path(model_dir) / "config.json").read_text())


_PREFIXES = ("bert.", "roberta.", "mpnet.", "model.", "electra.")


def _strip_prefix(name: str) -> str:
    for p in _PREFIXES:
        if name.startswith(p):
            return name[len(p):]
    return name


def convert_bert(
    state_dict: Dict[str, Any], cfg: BertConfig, with_pooler: bool = False
) -> Params:
    """Map an HF BERT/XLM-RoBERTa state_dict to the bert.py pytree."""
    sd = {_strip_prefix(k): _to_numpy(v) for k, v in state_dict.items()}

    def take(name: str) -> np.ndarray:
        if name not in sd:
            raise KeyError(f"checkpoint missing tensor {name!r}; have e.g. "
                           f"{sorted(sd)[:5]}")
        return sd[name].astype(np.float32)

    def linear(prefix: str) -> dict:
        return {"kernel": take(f"{prefix}.weight").T, "bias": take(f"{prefix}.bias")}

    def ln(prefix: str) -> dict:
        return {"scale": take(f"{prefix}.weight"), "bias": take(f"{prefix}.bias")}

    params: Params = {
        "embeddings": {
            "word_embeddings": take("embeddings.word_embeddings.weight"),
            "position_embeddings": take("embeddings.position_embeddings.weight"),
            "token_type_embeddings": (
                take("embeddings.token_type_embeddings.weight")
                if "embeddings.token_type_embeddings.weight" in sd
                else np.zeros((cfg.type_vocab_size, cfg.hidden_size), np.float32)
            ),
            "ln": ln("embeddings.LayerNorm"),
        },
        "layers": [],
    }
    for i in range(cfg.num_layers):
        p = f"encoder.layer.{i}"
        params["layers"].append(
            {
                "attention": {
                    "query": linear(f"{p}.attention.self.query"),
                    "key": linear(f"{p}.attention.self.key"),
                    "value": linear(f"{p}.attention.self.value"),
                    "out": linear(f"{p}.attention.output.dense"),
                    "ln": ln(f"{p}.attention.output.LayerNorm"),
                },
                "mlp": {
                    "in": linear(f"{p}.intermediate.dense"),
                    "out": linear(f"{p}.output.dense"),
                    "ln": ln(f"{p}.output.LayerNorm"),
                },
            }
        )
    if with_pooler:
        params["pooler"] = linear("pooler.dense")
        # cross-encoder classifier head lives outside the encoder prefix
        cls_key = "classifier.weight" if "classifier.weight" in sd else None
        if cls_key:
            params["classifier"] = {"kernel": take("classifier.weight").T,
                                    "bias": take("classifier.bias")}
    return params


def convert_gpt(state_dict: Dict[str, Any], cfg) -> Params:
    """Map an HF GPT-2 or Llama state_dict to the gpt.py pytree.

    GPT-2 uses Conv1D modules whose weights are already [in, out]; the fused
    c_attn [H, 3H] is split into q/k/v. Llama uses Linear ([out, in] →
    transposed) with separate q/k/v/o and SwiGLU gate/up/down.
    """
    import numpy as np

    sd = {_strip_prefix(k.replace("transformer.", "")): _to_numpy(v)
          for k, v in state_dict.items()}

    def take(name):
        if name not in sd:
            raise KeyError(f"checkpoint missing tensor {name!r}")
        return sd[name].astype(np.float32)

    params: Params = {"layers": []}
    if cfg.arch == "gpt2":
        params["wte"] = take("wte.weight")
        params["wpe"] = take("wpe.weight")
        params["ln_f"] = {"scale": take("ln_f.weight"), "bias": take("ln_f.bias")}
        H = cfg.hidden_size
        for i in range(cfg.num_layers):
            p = f"h.{i}"
            qkv_w = take(f"{p}.attn.c_attn.weight")  # [H, 3H] (Conv1D)
            qkv_b = take(f"{p}.attn.c_attn.bias")
            qw, kw, vw = np.split(qkv_w, 3, axis=1)
            qb, kb, vb = np.split(qkv_b, 3)
            params["layers"].append({
                "ln1": {"scale": take(f"{p}.ln_1.weight"), "bias": take(f"{p}.ln_1.bias")},
                "ln2": {"scale": take(f"{p}.ln_2.weight"), "bias": take(f"{p}.ln_2.bias")},
                "q": {"kernel": qw, "bias": qb},
                "k": {"kernel": kw, "bias": kb},
                "v": {"kernel": vw, "bias": vb},
                "o": {"kernel": take(f"{p}.attn.c_proj.weight"),
                      "bias": take(f"{p}.attn.c_proj.bias")},
                "mlp": {
                    "in": {"kernel": take(f"{p}.mlp.c_fc.weight"),
                           "bias": take(f"{p}.mlp.c_fc.bias")},
                    "out": {"kernel": take(f"{p}.mlp.c_proj.weight"),
                            "bias": take(f"{p}.mlp.c_proj.bias")},
                },
            })
    elif cfg.arch == "llama":
        params["wte"] = take("embed_tokens.weight")
        params["ln_f"] = {"scale": take("norm.weight")}
        for i in range(cfg.num_layers):
            p = f"layers.{i}"
            params["layers"].append({
                "ln1": {"scale": take(f"{p}.input_layernorm.weight")},
                "ln2": {"scale": take(f"{p}.post_attention_layernorm.weight")},
                "q": {"kernel": take(f"{p}.self_attn.q_proj.weight").T},
                "k": {"kernel": take(f"{p}.self_attn.k_proj.weight").T},
                "v": {"kernel": take(f"{p}.self_attn.v_proj.weight").T},
                "o": {"kernel": take(f"{p}.self_attn.o_proj.weight").T},
                "mlp": {
                    "gate": {"kernel": take(f"{p}.mlp.gate_proj.weight").T},
                    "up": {"kernel": take(f"{p}.mlp.up_proj.weight").T},
                    "down": {"kernel": take(f"{p}.mlp.down_proj.weight").T},
                },
            })
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"kernel": take("lm_head.weight").T}
    else:
        raise ValueError(f"unsupported arch {cfg.arch!r}")
    return params


def export_hf_bert(params: Params, cfg: BertConfig, out_dir: str | Path,
                   tokenizer_file: str | Path | None = None) -> Path:
    """Inverse of convert_bert: write a hub-format model dir
    (config.json + model.safetensors, torch tensor-name layout) from a bert.py
    pytree — so checkpoints trained IN this framework are loadable by both the
    engine's standard model_dir path and by `transformers` itself. Kernels go
    back to torch Linear's [out, in]; tensor names match what BertModel's own
    save_pretrained produces (no "bert." prefix — convert_bert strips either
    form)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    sd: Dict[str, np.ndarray] = {}

    def put_linear(prefix: str, p: dict) -> None:
        sd[f"{prefix}.weight"] = np.ascontiguousarray(
            np.asarray(p["kernel"], np.float32).T)
        sd[f"{prefix}.bias"] = np.asarray(p["bias"], np.float32)

    def put_ln(prefix: str, p: dict) -> None:
        sd[f"{prefix}.weight"] = np.asarray(p["scale"], np.float32)
        sd[f"{prefix}.bias"] = np.asarray(p["bias"], np.float32)

    emb = params["embeddings"]
    sd["embeddings.word_embeddings.weight"] = np.asarray(
        emb["word_embeddings"], np.float32)
    sd["embeddings.position_embeddings.weight"] = np.asarray(
        emb["position_embeddings"], np.float32)
    sd["embeddings.token_type_embeddings.weight"] = np.asarray(
        emb["token_type_embeddings"], np.float32)
    put_ln("embeddings.LayerNorm", emb["ln"])
    for i, layer in enumerate(params["layers"]):
        p = f"encoder.layer.{i}"
        put_linear(f"{p}.attention.self.query", layer["attention"]["query"])
        put_linear(f"{p}.attention.self.key", layer["attention"]["key"])
        put_linear(f"{p}.attention.self.value", layer["attention"]["value"])
        put_linear(f"{p}.attention.output.dense", layer["attention"]["out"])
        put_ln(f"{p}.attention.output.LayerNorm", layer["attention"]["ln"])
        put_linear(f"{p}.intermediate.dense", layer["mlp"]["in"])
        put_linear(f"{p}.output.dense", layer["mlp"]["out"])
        put_ln(f"{p}.output.LayerNorm", layer["mlp"]["ln"])
    if "pooler" in params:
        put_linear("pooler.dense", params["pooler"])
    if "classifier" in params:
        put_linear("classifier", params["classifier"])

    from safetensors.numpy import save_file

    # metadata format=pt: transformers refuses safetensors without it
    save_file(sd, str(out_dir / "model.safetensors"), metadata={"format": "pt"})
    # model_type must invert BertConfig.from_hf exactly: an XLM-RoBERTa-family
    # pytree (position_offset = pad_token_id + 1, e.g. the default
    # mpnet-multilingual model) written back as model_type='bert'/pad=0 would
    # reload with offset-0 position ids — silently wrong embeddings both here
    # and in transformers. from_hf derives offset from pad_token_id, so
    # pad_token_id = position_offset - 1 round-trips it.
    if cfg.position_offset:
        model_type, architectures = "xlm-roberta", ["XLMRobertaModel"]
        pad_token_id = cfg.position_offset - 1
    else:
        model_type, architectures = "bert", ["BertModel"]
        pad_token_id = 0
    hf_cfg = {
        "model_type": model_type,
        "architectures": architectures,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "intermediate_size": cfg.intermediate_size,
        "max_position_embeddings": cfg.max_position_embeddings,
        "type_vocab_size": cfg.type_vocab_size,
        "layer_norm_eps": cfg.layer_norm_eps,
        "hidden_act": cfg.hidden_act,
        "pad_token_id": pad_token_id,
    }
    (out_dir / "config.json").write_text(json.dumps(hf_cfg, indent=2))
    if tokenizer_file is not None:
        import shutil

        shutil.copyfile(tokenizer_file, out_dir / "tokenizer.json")
    return out_dir


def load_gpt_model(model_dir: str | Path):
    """One-call load: (params, GPTConfig) from a local HF model dir."""
    from symbiont_tpu.models.gpt import GPTConfig

    hf_cfg = load_hf_config(model_dir)
    cfg = GPTConfig.from_hf(hf_cfg)
    params = convert_gpt(load_state_dict(model_dir), cfg)
    return params, cfg


def load_bert_model(model_dir: str | Path, with_pooler: bool = False):
    """One-call load: (params, BertConfig) from a local HF model dir."""
    hf_cfg = load_hf_config(model_dir)
    cfg = BertConfig.from_hf(hf_cfg)
    params = convert_bert(load_state_dict(model_dir), cfg, with_pooler=with_pooler)
    return params, cfg


def main(argv=None) -> None:
    """CLI: convert a local HF checkpoint and cache the JAX pytree.

        python -m symbiont_tpu.models.convert <hf_model_dir> [--out DIR]
               [--kind auto|bert|gpt] [--pooler]

    With --out, the converted params land in a mmap-friendly checkpoint dir
    (symbiont_tpu.train.checkpoint format) so engine restarts skip
    reconversion (SURVEY.md §5.4 plan — the reference re-downloads and
    re-converts on every boot, embedding_generator.rs:25-58). Without --out,
    it's a dry run that validates the layout and prints the geometry."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m symbiont_tpu.models.convert", description=main.__doc__)
    ap.add_argument("model_dir", help="local HF model dir (safetensors/.bin + config.json)")
    ap.add_argument("--out", help="checkpoint dir to write converted params to")
    ap.add_argument("--kind", choices=["auto", "bert", "gpt"], default="auto")
    ap.add_argument("--pooler", action="store_true",
                    help="include pooler+classifier head (cross-encoders)")
    args = ap.parse_args(argv)

    hf_cfg = load_hf_config(args.model_dir)
    kind = args.kind
    if kind == "auto":
        kind = "gpt" if hf_cfg.get("model_type") in ("gpt2", "llama", "mistral") else "bert"
    if kind == "gpt":
        params, cfg = load_gpt_model(args.model_dir)
    else:
        params, cfg = load_bert_model(args.model_dir, with_pooler=args.pooler)
    import jax

    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    print(f"{kind}: {type(cfg).__name__} hidden={cfg.hidden_size} "
          f"layers={cfg.num_layers} heads={cfg.num_heads} — "
          f"{n_params / 1e6:.1f}M params converted OK")
    if args.out:
        import dataclasses

        from symbiont_tpu.train.checkpoint import save_params

        save_params(args.out, params,
                    meta={"kind": kind, "config": dataclasses.asdict(cfg),
                          "source": str(args.model_dir)})
        print(f"saved checkpoint to {args.out}")


if __name__ == "__main__":
    main()
