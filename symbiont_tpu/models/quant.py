"""Weight quantization for the BERT/GPT forwards — narrow HBM reads, fused
dequant.

ROADMAP item 4: both remaining hot paths are bandwidth-bound, not FLOP-bound
(mixed-length embed MFU 25.6%, TinyLlama decode HBM-bound at ~714 GB/s), so
the lever is moving fewer bytes per forward, per "Hardware Acceleration of
Fully Quantized BERT" (arxiv 2103.02800) and "Demystifying BERT" (arxiv
2104.08335). Three storage modes, all selected by a config knob
(`EngineConfig.quantize` / `LmConfig.quantize`) and applied ONCE on host at
load time:

- `f16`  — floating params of rank ≥ 2 stored bfloat16 at rest. The forward
  already computes in bf16, so the entry cast becomes a no-op and every
  weight read out of HBM is half the bytes of the f32-at-rest default.
- `int8` — symmetric per-channel int8 (scale over the LAST axis: the output
  features of an [in, out] kernel, the hidden dim of an embedding table).
  Dequant is algebraically fused into the consumer: `x @ W` becomes
  `(x @ q) * scale` (exact for per-output-channel scales), so XLA reads
  int8 from HBM, upcasts in registers, and never materializes a
  dequantized copy.
- `fp8`  — float8_e4m3fn storage with the same per-channel scale mapping
  each channel's amax to the e4m3 max (448). Same fused-dequant contract;
  coarser mantissa (3 bits) than int8's effective 7, so its parity bar is
  looser (docs/QUANTIZATION.md).

Quantized leaves are `QuantTensor` pytree nodes — (q, scale) ride through
jit / device_put / donation like any other params, and `cast_params` (the
shared entry-cast used by models/bert.py, models/gpt.py and engine/lm.py)
treats them as atomic leaves so the f32 scales are never downcast by the
compute-dtype sweep.

Rank-1 params (biases, norm scales) stay f32: they are a rounding error of
the byte budget and the norms want exact statistics.

The int8 KV-cache variant (quantize-on-append / dequant-on-attend) lives
with its consumer in models/gpt.py; this module only provides the shared
per-channel quantizer it uses.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from symbiont_tpu.config import QUANTIZE_MODES as MODES

Params = Any

_INT8_AMAX = 127.0
_FP8_AMAX = 448.0  # float8_e4m3fn finite max


@jax.tree_util.register_pytree_node_class
class QuantTensor:
    """A per-channel-quantized 2-D weight: `q` (int8 or fp8, [r, c]) and
    `scale` (f32, [c], over the LAST axis). Dequantized value = q * scale.
    Registered as a pytree node so it flows through jit/device_put; every
    cast-to-compute-dtype sweep must treat it as a leaf (cast_params)."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    def dequantize(self, dtype=jnp.float32):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def is_quantized(x) -> bool:
    return isinstance(x, QuantTensor)


def _leaf(x) -> bool:
    return isinstance(x, QuantTensor)


def channel_quantize(w, amax: float, qdtype) -> QuantTensor:
    """Symmetric per-channel quantization over the last axis. Host-side,
    runs once at load."""
    wf = jnp.asarray(w, jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=tuple(range(wf.ndim - 1))) / amax
    scale = jnp.maximum(scale, 1e-12)
    q = wf / scale
    if jnp.issubdtype(jnp.dtype(qdtype), jnp.integer):
        q = jnp.round(q)
    return QuantTensor(q.astype(qdtype), scale.astype(jnp.float32))


def quantize_params(params: Params, mode: str) -> Params:
    """Quantize every floating leaf of rank ≥ 2 (matmul kernels, embedding
    tables) per `mode`; rank-1 leaves (biases, norm params) stay f32.
    Idempotent on already-quantized leaves. Runs ONCE on host."""
    if mode not in MODES:
        raise ValueError(f"quantize must be one of {MODES}, got {mode!r}")
    if mode == "none":
        return params

    def one(a):
        if isinstance(a, QuantTensor):
            return a
        if not (hasattr(a, "dtype") and hasattr(a, "ndim")
                and jnp.issubdtype(a.dtype, jnp.floating) and a.ndim >= 2):
            return a
        if mode == "f16":
            return jnp.asarray(a, jnp.bfloat16)
        if mode == "int8":
            return channel_quantize(a, _INT8_AMAX, jnp.int8)
        return channel_quantize(a, _FP8_AMAX, jnp.float8_e4m3fn)

    return jax.tree.map(one, params, is_leaf=_leaf)


def cast_params(params: Params, dtype) -> Params:
    """The shared entry cast: floating leaves → compute dtype, QuantTensor
    leaves untouched (their f32 scales must survive the sweep — dequant
    precision rides on them)."""
    dtype = jnp.dtype(dtype)

    def cast(a):
        if isinstance(a, QuantTensor):
            return a
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            return a.astype(dtype)
        return a

    return jax.tree.map(cast, params, is_leaf=_leaf)


def param_bytes(params: Params) -> int:
    """At-rest parameter bytes of a (possibly quantized) pytree — the
    dtype-labeled `engine.param_bytes` / `lm.param_bytes` gauges."""
    total = 0
    for leaf in jax.tree.leaves(params, is_leaf=_leaf):
        if isinstance(leaf, QuantTensor):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


# ------------------------------------------------------- fused-dequant ops

def mm(x, w):
    """`x @ w` with dequant fused into the matmul epilogue when `w` is
    quantized: per-output-channel scales commute with the contraction, so
    `(x @ q) * scale` is exactly `x @ (q * scale)` — HBM reads the narrow
    `q`, the scale multiply runs on the [.., out] result in registers."""
    if isinstance(w, QuantTensor):
        return ((x @ w.q.astype(x.dtype)) * w.scale).astype(x.dtype)
    return x @ w


def mm_tied(x, w):
    """`x @ w.T` for a tied embedding head. The scale axis (hidden) is the
    CONTRACTION axis after the transpose, so it is applied to `x` first:
    `(x * scale) @ q.T` == `x @ (q * scale).T` exactly."""
    if isinstance(w, QuantTensor):
        return (x * w.scale).astype(x.dtype) @ w.q.T.astype(x.dtype)
    return x @ w.T


def take(w, ids):
    """Embedding-table gather with per-hidden-channel dequant: `q[ids] *
    scale` (scale is over the hidden axis, exact per element). Returns f32
    for quantized tables — callers cast the summed embedding to compute
    dtype, which they already do for the unquantized path."""
    if isinstance(w, QuantTensor):
        return w.q[ids].astype(jnp.float32) * w.scale
    return w[ids]


def kv_channel_quantize(t, eps: float = 1e-8):
    """Quantize-on-append for the int8 KV cache (models/gpt.py): one scale
    per appended (batch, position, kv-head) vector over head_dim, so each
    head's fresh K/V row maps its own amax to ±127. Returns (q int8,
    scale f32 [..., heads])."""
    tf = t.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(tf), axis=-1), eps) / _INT8_AMAX
    q = jnp.round(tf / scale[..., None]).astype(jnp.int8)
    return q, scale


def kv_dequantize(q, scale, dtype):
    """Dequant-on-attend: int8 cache slab * its per-head scales → compute
    dtype. The f32 intermediate never leaves registers; HBM reads int8 +
    the (head_dim× smaller) scale plane."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)
