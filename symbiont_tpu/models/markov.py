"""Order-1 word-level Markov chain — behavioral parity with the reference's
text generator (reference: services/text_generator_service/src/main.rs:13-109).

Semantics kept exactly:
- train: whitespace split; <2 words → record starter only (if any) and skip
  chain building; starters deduped; transitions are a multiset (duplicates
  weight the random walk) (reference: main.rs:29-80);
- generate: uniform-random starter, then up to max_length-1 uniform picks from
  the current word's successor list, stopping at a dead end; untrained model →
  the literal string "Model not trained." (reference: main.rs:82-108).

Beyond parity, `train` here accepts incremental corpus updates (the reference
retrains only on one hardcoded sentence at boot, main.rs:169-174, losing all
learned state each restart — SURVEY.md §5.4); our text_generator service feeds
it every ingested document and the state participates in checkpointing.
"""

from __future__ import annotations

import random
from typing import Dict, List


class MarkovModel:
    def __init__(self) -> None:
        self.chain: Dict[str, List[str]] = {}
        self.starters: List[str] = []

    def train(self, text: str) -> None:
        if not text:
            return
        words = text.split()
        if len(words) < 2:
            if words:
                self.starters.append(words[0])
                self._dedup_starters()
            return
        self.starters.append(words[0])
        for cur, nxt in zip(words, words[1:]):
            self.chain.setdefault(cur, []).append(nxt)
        self._dedup_starters()

    def _dedup_starters(self) -> None:
        # reference sorts + dedups after every train (main.rs:60-61)
        self.starters = sorted(set(self.starters))

    def generate(self, max_length: int, rng: random.Random | None = None) -> str:
        if not self.chain or not self.starters:
            return "Model not trained."
        rng = rng or random
        current = rng.choice(self.starters)
        out = [current]
        for _ in range(max_length - 1):
            nxt_words = self.chain.get(current)
            if not nxt_words:
                break
            current = rng.choice(nxt_words)
            out.append(current)
        return " ".join(out)

    # -- persistence (not in reference; supports checkpoint/resume §5.4) -----

    def to_state(self) -> dict:
        return {"chain": self.chain, "starters": self.starters}

    @classmethod
    def from_state(cls, state: dict) -> "MarkovModel":
        m = cls()
        m.chain = {k: list(v) for k, v in state["chain"].items()}
        m.starters = list(state["starters"])
        return m
