"""Single-source wire schema.

The reference keeps its wire schema in Rust serde structs
(reference: libs/shared_models/src/lib.rs:3-110) and hand-duplicates the same
shapes as TypeScript interfaces in the frontend
(reference: frontend/src/app/page.tsx:7-48) — an acknowledged hand-sync hazard.
Here the schema has exactly ONE source (these dataclasses); the C++ header and
TS interfaces are *generated* from it (see symbiont_tpu.schema.codegen), so the
sync bug class cannot exist.

All 13 wire structs from the reference are present with identical field names
and JSON shapes, so the reference frontend and any NATS-speaking peer remain
wire-compatible. Optional fields serialize as JSON null (serde's Option
behavior).
"""

from __future__ import annotations

import dataclasses
import json
import typing
from dataclasses import dataclass, field
from typing import Any, List, Optional, Type, TypeVar, get_args, get_origin

T = TypeVar("T")

# Registry of all wire structs, in reference declaration order
# (reference: libs/shared_models/src/lib.rs:3-110).
WIRE_TYPES: list[type] = []


def wire(cls: type) -> type:
    """Register a dataclass as a wire struct (adds JSON round-trip methods)."""
    cls = dataclass(cls)
    cls.__wire_hints__ = typing.get_type_hints(cls)  # cached: decode hot path
    WIRE_TYPES.append(cls)
    return cls


_U64_SAFE_MAX = 2**53  # double-mantissa bound shared with the C++ decoder


def _encode(value: Any) -> Any:
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {f.name: _encode(getattr(value, f.name)) for f in dataclasses.fields(value)}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, int) and not isinstance(value, bool):
        # wire ints are u64 (serde side); enforce at the producer so a bad
        # value fails here, not in a remote C++ worker's as_u64()
        if not 0 <= value < _U64_SAFE_MAX:
            raise ValueError(f"integer {value} outside u64-safe range [0, 2^53)")
    return value


def _decode(tp: Any, value: Any) -> Any:
    origin = get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in get_args(tp) if a is not type(None)]
        if value is None:
            return None
        return _decode(args[0], value)
    if origin in (list, List):
        if not isinstance(value, list):
            raise ValueError(f"expected array, got {type(value).__name__}")
        (elem,) = get_args(tp)
        return [_decode(elem, v) for v in value]
    if dataclasses.is_dataclass(tp):
        return from_dict(tp, value)
    if tp is float:
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise ValueError(f"expected number, got {type(value).__name__}")
        return float(value)
    if tp is int:
        if not isinstance(value, int) or isinstance(value, bool):
            raise ValueError(f"expected integer, got {type(value).__name__}")
        if not 0 <= value < _U64_SAFE_MAX:
            raise ValueError(f"integer {value} outside u64-safe range [0, 2^53)")
        return value
    if tp is bool:
        if not isinstance(value, bool):
            raise ValueError(f"expected boolean, got {type(value).__name__}")
        return value
    if tp is str and not isinstance(value, str):
        raise ValueError(f"expected string, got {type(value).__name__}")
    return value


def from_dict(cls: Type[T], data: dict) -> T:
    """Strict decode: unknown fields rejected, missing non-optional fields raise."""
    hints = getattr(cls, "__wire_hints__", None) or typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _decode(hints[f.name], data[f.name])
        elif _is_optional(hints[f.name]):
            kwargs[f.name] = None
        else:
            raise ValueError(f"{cls.__name__}: missing required field {f.name!r}")
    unknown = set(data) - {f.name for f in dataclasses.fields(cls)}
    if unknown:
        raise ValueError(f"{cls.__name__}: unknown fields {sorted(unknown)}")
    return cls(**kwargs)


def _is_optional(tp: Any) -> bool:
    return get_origin(tp) is typing.Union and type(None) in get_args(tp)


def to_json(msg: Any) -> str:
    # allow_nan=False: a NaN/Inf embedding value must fail at the producer,
    # not poison the bus for serde_json/C++ consumers.
    return json.dumps(_encode(msg), ensure_ascii=False, separators=(",", ":"),
                      allow_nan=False)


def to_json_bytes(msg: Any) -> bytes:
    return to_json(msg).encode("utf-8")


def from_json(cls: Type[T], raw: str | bytes) -> T:
    if isinstance(raw, (bytes, bytearray)):
        raw = raw.decode("utf-8")
    return from_dict(cls, json.loads(raw))


# ---------------------------------------------------------------------------
# The 13 wire structs (reference: libs/shared_models/src/lib.rs:3-110)
# ---------------------------------------------------------------------------


@wire
class PerceiveUrlTask:
    """reference: libs/shared_models/src/lib.rs:4-6"""

    url: str


@wire
class RawTextMessage:
    """reference: libs/shared_models/src/lib.rs:9-14"""

    id: str
    source_url: str
    raw_text: str
    timestamp_ms: int


@wire
class TokenizedTextMessage:
    """reference: libs/shared_models/src/lib.rs:17-23"""

    original_id: str
    source_url: str
    tokens: List[str]
    sentences: List[str]
    timestamp_ms: int


@wire
class GenerateTextTask:
    """reference: libs/shared_models/src/lib.rs:26-30

    `stream`, `temperature` and `top_k` are this framework's additions:
    when true (and an LM backend with streaming is active), `stream` sends
    token deltas out on events.text.generated.partial while decoding;
    `temperature`/`top_k` override the LM engine's sampling defaults per
    request (temperature 0 = greedy; ignored by the Markov backend, which
    has no sampling knobs). All optional, so reference-era clients (which
    omit them) remain wire-compatible — and unstreamed requests keep riding
    the generation micro-batcher."""

    task_id: str
    prompt: Optional[str]
    max_length: int
    stream: Optional[bool] = None
    temperature: Optional[float] = None
    top_k: Optional[int] = None


@wire
class GeneratedTextMessage:
    """reference: libs/shared_models/src/lib.rs:33-37"""

    original_task_id: str
    generated_text: str
    timestamp_ms: int


@wire
class SentenceEmbedding:
    """reference: libs/shared_models/src/lib.rs:40-43"""

    sentence_text: str
    embedding: List[float]


@wire
class TextWithEmbeddingsMessage:
    """reference: libs/shared_models/src/lib.rs:46-52"""

    original_id: str
    source_url: str
    embeddings_data: List[SentenceEmbedding]
    model_name: str
    timestamp_ms: int


@wire
class SemanticSearchApiRequest:
    """reference: libs/shared_models/src/lib.rs:55-58

    `rerank` is this framework's addition (BASELINE.md config #4): when true,
    the gateway reranks the top-k hits with the cross-encoder and replaces
    each hit's score with the cross-encoder relevance score. Optional, so
    reference-era clients (which omit it) remain wire-compatible.
    """

    query_text: str
    top_k: int
    rerank: Optional[bool] = None


@wire
class QueryForEmbeddingTask:
    """reference: libs/shared_models/src/lib.rs:61-64"""

    request_id: str
    text_to_embed: str


@wire
class QueryEmbeddingResult:
    """reference: libs/shared_models/src/lib.rs:67-72"""

    request_id: str
    embedding: Optional[List[float]]
    model_name: Optional[str]
    error_message: Optional[str]


@wire
class QdrantPointPayload:
    """reference: libs/shared_models/src/lib.rs:75-82

    Name kept for wire parity even though our vector store is TPU-native
    (symbiont_tpu.memory), not Qdrant.
    """

    original_document_id: str
    source_url: str
    sentence_text: str
    sentence_order: int
    model_name: str
    processed_at_ms: int


@wire
class SemanticSearchNatsTask:
    """reference: libs/shared_models/src/lib.rs:85-89"""

    request_id: str
    query_embedding: List[float]
    top_k: int


@wire
class SemanticSearchResultItem:
    """reference: libs/shared_models/src/lib.rs:92-96"""

    qdrant_point_id: str
    score: float
    payload: QdrantPointPayload


@wire
class SemanticSearchNatsResult:
    """reference: libs/shared_models/src/lib.rs:99-103"""

    request_id: str
    results: List[SemanticSearchResultItem]
    error_message: Optional[str]


@wire
class SemanticSearchApiResponse:
    """reference: libs/shared_models/src/lib.rs:106-110"""

    search_request_id: str
    results: List[SemanticSearchResultItem]
    error_message: Optional[str]


@wire
class GeneratedTextChunk:
    """This framework's addition (no reference equivalent): a streaming
    delta on events.text.generated.partial. The final full text still goes
    out as GeneratedTextMessage on events.text.generated, so reference-era
    consumers are unaffected; streaming clients append deltas by
    (original_task_id, seq) and stop at done=true."""

    original_task_id: str
    text_delta: str
    seq: int
    done: bool
    timestamp_ms: int


__all__ = [t.__name__ for t in WIRE_TYPES] + [
    "WIRE_TYPES",
    "to_json",
    "to_json_bytes",
    "from_json",
    "from_dict",
]
