"""Binary tensor frames — the zero-copy bulk-float data plane on the bus.

docs/PERF.md attributes the 5.5× gap between full-stack ingest and the
engine-plane bulk number largely to host-side (de)serialization: every
embedding hop used to JSON-encode 384 floats per sentence, and each f32
that rode through Python `float()` serialized as the ~17-digit shortest
round-trip of its DOUBLE widening (~19-20 bytes per float on the wire).
The accelerator-feeding literature makes the same point (Demystifying
BERT, arxiv 2104.08335; LightSeq, arxiv 2010.13887): for small encoder
models, host serialization and data movement — not the forward pass — is
where throughput dies.

A tensor frame is a fixed 16-byte header + packed little-endian f32 rows:

    offset 0   magic  b"SYTF"
    offset 4   u8     version (1)
    offset 5   u8     dtype   (1 = f32 little-endian, 2 = f16 little-endian)
    offset 6   u16le  reserved (0)
    offset 8   u32le  rows
    offset 12  u32le  cols
    offset 16  rows * cols * elem_size bytes, row-major (elem_size 4 for
               f32, 2 for the half-width f16 form; consumers upcast on
               ingest — VectorStore.upsert_rows takes any float dtype)

The frame rides APPENDED to the ordinary JSON message body; the
`X-Symbiont-Frame` content-type header (`tensor/f32;off=<n>`, where `n`
is the JSON prefix length in bytes) announces it. JSON metadata — ids,
sentence texts, source url — stays in the JSON prefix, which remains a
schema-valid message whose per-sentence `embedding` lists are empty.
Decode is `np.frombuffer` — a zero-copy view, no per-float Python
object is ever materialized.

Negotiation and the fallback contract:

- request-reply (engine plane): the REQUESTER opts in per call with
  `"encoding": "frame"`; an old engine ignores the unknown value and
  replies with JSON float lists, which every caller still accepts.
- pub/sub (data.text.with_embeddings): a broadcast has no per-consumer
  negotiation, so the publisher side is a deployment knob —
  `SYMBIONT_FRAMES` (default on; set `0` when a reference-era JSON-only
  consumer shares the subject). With frames off, the encoder emits the
  exact reference wire shape (float lists), byte-compatible with any
  serde_json peer. Frame-capable consumers accept BOTH forms always, so
  mixed old/new fleets interoperate in either direction.

The native C++ mirror of this codec lives in native/services/common.hpp
(make_frame / split_frame); tests/test_frames.py pins the byte layout
with golden fixtures shared by both implementations.
"""

from __future__ import annotations

import json
import os
import struct
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from symbiont_tpu.schema import (
    SentenceEmbedding,
    TextWithEmbeddingsMessage,
    from_json,
    to_json_bytes,
)
from symbiont_tpu.utils.telemetry import metrics

FRAME_HEADER = "X-Symbiont-Frame"
# Request-reply negotiation for REPLY frames on reference-parity schema
# subjects (tasks.embedding.for_query): the requester announces frame
# capability with this HEADER instead of a schema field — the wire body
# stays byte-identical for reference-era peers, and a peer that has never
# heard of the header simply ignores it and replies JSON float lists (the
# fallback every caller accepts). Engine-plane subjects keep their in-body
# `"encoding": "frame"` negotiation (framework-internal JSON, no parity
# constraint).
ACCEPT_FRAME_HEADER = "X-Symbiont-Accept-Frame"
FRAME_MAGIC = b"SYTF"
FRAME_VERSION = 1
DTYPE_F32 = 1
DTYPE_F16 = 2  # IEEE half — half the bytes/embedding on every frame hop
# magic, version, dtype, reserved, rows, cols — 16 bytes, little-endian
_HDR = struct.Struct("<4sBBHII")
FRAME_HDR_LEN = _HDR.size

# ONE home for the dtype registry: name ↔ header byte ↔ numpy dtype ↔
# content type. Services never hard-code any of these (statically banned
# outside one allowlisted encoder — tests/test_pipeline_wiring.py); a new
# dtype is added HERE and nowhere else.
_DTYPE_BY_NAME = {"f32": DTYPE_F32, "f16": DTYPE_F16}
_NAME_BY_DTYPE = {v: k for k, v in _DTYPE_BY_NAME.items()}
_NP_BY_DTYPE = {DTYPE_F32: "<f4", DTYPE_F16: "<f2"}
_SIZE_BY_DTYPE = {DTYPE_F32: 4, DTYPE_F16: 2}
_CONTENT_TYPE_BY_DTYPE = {code: f"tensor/{name}"
                          for name, code in _DTYPE_BY_NAME.items()}
_KNOWN_CONTENT_TYPES = set(_CONTENT_TYPE_BY_DTYPE.values())


class FrameError(ValueError):
    """Malformed frame or frame/metadata mismatch (handler-fatal: the
    delivery stays unacked for redelivery / DLQ, never silently dropped)."""


def wants_frame(headers: Optional[Dict[str, str]]) -> bool:
    """True when the requester announced frame capability for the REPLY
    (ACCEPT_FRAME_HEADER: "1"). Absent/other → reply JSON float lists."""
    return (headers or {}).get(ACCEPT_FRAME_HEADER) == "1"


def frames_mode(default: str = "f32") -> str:
    """Publisher-side deployment knob for the pub/sub hops, now three-way:
    "off" (reference wire JSON), "f32" (the default frame form every
    frame-capable peer decodes), or "f16" (half-width rows — deploy only
    when every consumer on the subject decodes dtype 2; an f32-only
    consumer FrameErrors the delivery into redelivery/DLQ rather than
    ingesting garbage, see docs/QUANTIZATION.md). Request-reply paths
    negotiate per call instead (`encoding` / ACCEPT_FRAME_HEADER)."""
    v = os.environ.get("SYMBIONT_FRAMES", "").strip().lower()
    if not v:
        return default
    if v in ("0", "false", "no", "off"):
        return "off"
    if v in _DTYPE_BY_NAME:
        return v
    return "f32"


def frames_enabled(default: bool = True) -> bool:
    """Back-compat boolean view of frames_mode (the pre-f16 knob)."""
    return frames_mode("f32" if default else "off") != "off"


def _estimate_json_bytes_per_float() -> float:
    """Measured-once estimate of what one embedding float costs as wire
    JSON (the `frame.json_equiv_bytes` counter's multiplier): a seeded f32
    sample through the exact legacy path (f32 → Python float → json.dumps),
    which serializes as the shortest round-trip of the DOUBLE widening.
    The serialization bench tier measures the real ratio per run; this
    constant only feeds the obs counters."""
    rng = np.random.default_rng(0)
    sample = rng.standard_normal(64).astype(np.float32).tolist()
    return (len(json.dumps(sample, separators=(",", ":"))) - 1) / len(sample)


JSON_BYTES_PER_FLOAT_EST = _estimate_json_bytes_per_float()


# ----------------------------------------------------------------- raw codec

def encode_frame(rows: np.ndarray, dtype: str = "f32") -> bytes:
    """Pack a [rows, cols] float array as one frame (header + packed
    little-endian rows in `dtype`: "f32" or the half-width "f16")."""
    code = _DTYPE_BY_NAME.get(dtype)
    if code is None:
        raise FrameError(f"unsupported frame dtype {dtype!r} "
                         f"(known: {sorted(_DTYPE_BY_NAME)})")
    with np.errstate(over="ignore"):  # overflow handled explicitly below
        arr = np.ascontiguousarray(np.asarray(rows,
                                              dtype=_NP_BY_DTYPE[code]))
    if arr.ndim != 2:
        raise FrameError(f"frame payload must be 2-D, got shape {arr.shape}")
    if code == DTYPE_F16 and np.isinf(arr).any():
        src = np.asarray(rows)
        if (np.isinf(arr) & np.isfinite(src)).any():
            # a finite value beyond ±65504 became inf in the half cast:
            # refuse to frame rather than ship silent corruption (an inf
            # row poisons every cosine against it downstream). Same
            # loud-failure stance as an undecodable dtype byte.
            raise FrameError(
                "value(s) exceed the f16 range (|x| > 65504): refusing to "
                "encode a half-width frame that would overflow to inf — "
                "use the f32 form for unnormalized payloads")
    t0 = time.perf_counter()
    out = _HDR.pack(FRAME_MAGIC, FRAME_VERSION, code, 0,
                    arr.shape[0], arr.shape[1]) + arr.tobytes()
    labels = {"dtype": dtype}
    metrics.inc("frame.encoded", labels=labels)
    metrics.inc("frame.bytes", len(out), labels=labels)
    metrics.inc("frame.json_equiv_bytes",
                arr.size * JSON_BYTES_PER_FLOAT_EST, labels=labels)
    metrics.observe("frame.encode_s", time.perf_counter() - t0)
    return out


def decode_frame(buf: bytes, offset: int = 0) -> np.ndarray:
    """Decode a frame starting at `offset` into a zero-copy read-only
    [rows, cols] view over `buf` (f32, or f16 for dtype-2 frames — the
    store upcasts on ingest). A dtype byte this peer does not implement
    raises FrameError — the delivery stays unacked for redelivery/DLQ,
    never silently misparsed."""
    t0 = time.perf_counter()
    if len(buf) - offset < FRAME_HDR_LEN:
        raise FrameError("frame truncated before header")
    magic, version, dtype, _, rows, cols = _HDR.unpack_from(buf, offset)
    if magic != FRAME_MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if version != FRAME_VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if dtype not in _NP_BY_DTYPE:
        raise FrameError(
            f"unsupported frame dtype {dtype} (this peer implements "
            f"{sorted(_NAME_BY_DTYPE.values())})")
    need = rows * cols * _SIZE_BY_DTYPE[dtype]
    body = offset + FRAME_HDR_LEN
    if len(buf) - body < need:
        raise FrameError(f"frame payload truncated: need {need} bytes, "
                         f"have {len(buf) - body}")
    arr = np.frombuffer(buf, dtype=_NP_BY_DTYPE[dtype], count=rows * cols,
                        offset=body).reshape(rows, cols)
    metrics.inc("frame.decoded", labels={"dtype": _NAME_BY_DTYPE[dtype]})
    metrics.observe("frame.decode_s", time.perf_counter() - t0)
    return arr


# ------------------------------------------------------------ bus attachment

def attach_frame(json_bytes: bytes, rows: np.ndarray,
                 dtype: str = "f32") -> Tuple[bytes, Dict[str, str]]:
    """JSON body + frame → (wire data, headers to merge into the publish)."""
    data = bytes(json_bytes) + encode_frame(rows, dtype=dtype)
    content = _CONTENT_TYPE_BY_DTYPE[_DTYPE_BY_NAME[dtype]]
    return data, {FRAME_HEADER: f"{content};off={len(json_bytes)}"}


def frame_offset(headers: Optional[Dict[str, str]]) -> Optional[int]:
    """Parse the X-Symbiont-Frame header; None when the message carries no
    frame. Raises FrameError on a malformed header value (the binary dtype
    byte stays authoritative — the content type only gates known names)."""
    value = (headers or {}).get(FRAME_HEADER)
    if value is None:
        return None
    parts = value.split(";")
    if parts[0].strip() not in _KNOWN_CONTENT_TYPES:
        raise FrameError(f"unknown frame content type {parts[0]!r}")
    for p in parts[1:]:
        k, _, v = p.strip().partition("=")
        if k == "off":
            try:
                off = int(v)
            except ValueError:
                raise FrameError(f"bad frame offset {v!r}") from None
            if off < 0:
                raise FrameError(f"negative frame offset {off}")
            return off
    raise FrameError(f"frame header missing off=: {value!r}")


def detach_frame(data: bytes, headers: Optional[Dict[str, str]]
                 ) -> Tuple[bytes, Optional[np.ndarray]]:
    """Split a possibly-frame-bearing body into (json bytes, rows-or-None).
    A frameless message passes through untouched — the JSON fallback."""
    off = frame_offset(headers)
    if off is None:
        return data, None
    if off > len(data):
        raise FrameError(f"frame offset {off} beyond body ({len(data)} bytes)")
    return data[:off], decode_frame(data, off)


# ------------------------------------------- data.text.with_embeddings codec

def encode_embeddings_message(original_id: str, source_url: str,
                              sentences: Sequence[str],
                              vectors, model_name: str, timestamp_ms: int,
                              use_frame: Optional[bool] = None,
                              wire_dtype: Optional[str] = None
                              ) -> Tuple[bytes, Dict[str, str]]:
    """Build the data.text.with_embeddings wire form. Frame mode keeps the
    floats out of JSON entirely (`wire_dtype` "f32" or half-width "f16";
    None resolves the SYMBIONT_FRAMES knob at publish time); fallback mode
    (`use_frame=False` or SYMBIONT_FRAMES=0) emits the exact reference wire
    shape so a JSON-only peer ingests it unchanged."""
    if use_frame is None:
        use_frame = frames_enabled()
    if wire_dtype is None:
        mode = frames_mode()
        wire_dtype = mode if mode in _DTYPE_BY_NAME else "f32"
    arr = np.ascontiguousarray(np.asarray(vectors, dtype=np.float32))
    if arr.ndim != 2 or arr.shape[0] != len(sentences):
        raise FrameError(
            f"vectors shape {arr.shape} does not match {len(sentences)} "
            "sentences")
    if use_frame:
        embeddings: List[SentenceEmbedding] = [
            SentenceEmbedding(sentence_text=s, embedding=[])
            for s in sentences]
    else:
        # ndarray.tolist() converts in C — no per-float Python loop even on
        # the fallback path (same double-widened digits as the old
        # `[float(x) for x in v]`, so the bytes stay wire-identical)
        embeddings = [
            SentenceEmbedding(sentence_text=s, embedding=row)
            for s, row in zip(sentences, arr.tolist())]
    out = TextWithEmbeddingsMessage(
        original_id=original_id, source_url=source_url,
        embeddings_data=embeddings, model_name=model_name,
        timestamp_ms=timestamp_ms)
    body = to_json_bytes(out)
    if not use_frame:
        return body, {}
    return attach_frame(body, arr, dtype=wire_dtype)


class LazyEmbeddingsMessage:
    """Zero-churn view over a data.text.with_embeddings body: scalar
    metadata + sentence texts pulled straight out of the parsed JSON dict,
    and the embedding block as ONE [n, dim] f32 ndarray — no per-sentence
    SentenceEmbedding/TextWithEmbeddingsMessage dataclasses are ever
    materialized. On the ingest hot path the consumer builds store payload
    dicts directly from these fields (services/vector_memory.py), so a
    message costs one json.loads and one array view, not 2n+1 Python
    object constructions."""

    __slots__ = ("original_id", "source_url", "model_name", "timestamp_ms",
                 "sentences", "rows")

    def __init__(self, original_id: str, source_url: str, model_name: str,
                 timestamp_ms: int, sentences: List[str], rows: np.ndarray):
        self.original_id = original_id
        self.source_url = source_url
        self.model_name = model_name
        self.timestamp_ms = timestamp_ms
        self.sentences = sentences
        self.rows = rows


def decode_embeddings_lazy(data: bytes,
                           headers: Optional[Dict[str, str]] = None
                           ) -> LazyEmbeddingsMessage:
    """Decode either wire form WITHOUT the per-sentence dataclass churn of
    `decode_embeddings_message`. Frame-bearing messages hand back the
    zero-copy row view; the JSON fallback converts its float lists to one
    f32 block (a single C-level np.asarray, no per-float Python loop).
    Malformed bodies raise (KeyError/TypeError/FrameError) — handler-fatal,
    same stance as from_json: the delivery stays unacked for redelivery."""
    json_bytes, rows = detach_frame(data, headers)
    d = json.loads(json_bytes)
    emb = d["embeddings_data"]
    sentences = [e["sentence_text"] for e in emb]
    if rows is None:
        lists = [e["embedding"] for e in emb]
        rows = (np.asarray(lists, dtype=np.float32) if lists
                else np.zeros((0, 0), np.float32))
        if rows.ndim != 2:
            raise FrameError(
                "embedding lists are ragged or non-numeric: cannot form "
                f"a [{len(lists)}, dim] block")
    elif rows.shape[0] != len(sentences):
        raise FrameError(
            f"frame carries {rows.shape[0]} rows for "
            f"{len(sentences)} sentences")
    return LazyEmbeddingsMessage(
        original_id=d["original_id"], source_url=d["source_url"],
        model_name=d["model_name"], timestamp_ms=int(d["timestamp_ms"]),
        sentences=sentences, rows=rows)


def decode_embeddings_message(data: bytes,
                              headers: Optional[Dict[str, str]] = None
                              ) -> Tuple[TextWithEmbeddingsMessage,
                                         Optional[np.ndarray]]:
    """Decode either wire form. Returns (message, rows): `rows` is the
    zero-copy [n_sentences, dim] view when a frame rode along (the
    message's `embedding` lists are empty then), or None for the JSON
    fallback (floats live in the message as usual)."""
    json_bytes, rows = detach_frame(data, headers)
    msg = from_json(TextWithEmbeddingsMessage, json_bytes)
    if rows is not None and rows.shape[0] != len(msg.embeddings_data):
        raise FrameError(
            f"frame carries {rows.shape[0]} rows for "
            f"{len(msg.embeddings_data)} sentences")
    return msg, rows
