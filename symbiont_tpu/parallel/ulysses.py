"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second of the two standard sequence-parallel schemes (the first, ring
attention, is in ring_attention.py). Where ring attention keeps queries local
and streams K/V blocks around the mesh, Ulysses re-shards with two
all-to-alls: entering attention, each device trades its sequence shard for a
head shard (so it holds the FULL sequence for NH/n heads and runs plain dense
attention — ideal for the MXU, one big matmul, no streaming-softmax carry);
leaving attention, the inverse all-to-all restores sequence sharding. Both
transposes ride ICI as a single collective each.

Trade-offs vs ring (why we ship both):
- Ulysses needs NH divisible by the axis size and moves Q, K, V and the
  output once each (4 all-to-alls of the full activation per attention);
  ring moves only K/V but n-1 times each.
- Ulysses composes head-parallelism-style with any attention kernel (the
  inner attention is just full attention, so the pallas flash kernel drops
  in); ring dictates its own blockwise streaming softmax.

The reference has no sequence parallelism of any kind — it hard-truncates to
one model's max length (reference:
services/preprocessing_service/src/embedding_generator.rs:93-99; SURVEY.md
§5.7). Exactness is tested against full attention on the 8-virtual-device CPU
mesh (tests/test_parallel.py).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from symbiont_tpu.parallel.compat import axis_size, shard_map


def _full_attention(q, k, v, causal: bool) -> jax.Array:
    """Plain dense attention, fp32 statistics. [B, S, H, D] layout."""
    B, S, H, D = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,  # [B, S_loc, NH, D] — local sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over the full (sharded) sequence; call inside
    shard_map. Requires NH % axis_size == 0."""
    n = axis_size(axis_name)
    NH = q.shape[2]
    if NH % n != 0:
        raise ValueError(f"num_heads {NH} not divisible by axis size {n}")

    # seq-sharded → head-sharded: split heads across the axis, gather the
    # sequence (device order along the axis == global sequence order)
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)  # [B, S, NH/n, D]

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _full_attention(qh, kh, vh, causal)
    # head-sharded → seq-sharded (inverse transpose)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)  # [B, S_loc, NH, D]


def ulysses_attention_sharded(
    q: jax.Array,  # [B, S, NH, D] — full sequence (host view)
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "data",
    causal: bool = False,
) -> jax.Array:
    """Convenience wrapper: shard the sequence dim over `axis_name` and run
    Ulysses attention; returns the full [B, S, NH, D] result."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(ulysses_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
