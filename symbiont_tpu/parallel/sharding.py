"""Sharding specs: how params and batches lay out on the mesh.

Design per the scaling-book recipe: pick a mesh, annotate shardings with
NamedSharding/PartitionSpec, let XLA insert the collectives. Nothing here
issues a collective by hand except ring attention (which needs the explicit
ppermute schedule).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Any


def replicate(mesh: Mesh, tree: Params) -> Params:
    """Fully replicate a pytree across the mesh (embedding models: weights are
    small; DP wants replicas)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard dim 0 (batch) over the data axis; everything else replicated."""
    return NamedSharding(mesh, P(axis))


def _gpt_layer_spec(arch: str) -> dict:
    """TP rules for one decoder layer: attention heads and MLP hidden shard on
    'tensor'; output projections shard the contracting dim so XLA reduces the
    partial sums with a psum over 'tensor'."""
    col = P(None, "tensor")  # [in, out] sharded on out
    row = P("tensor", None)  # [in, out] sharded on in  (contraction → psum)
    vec = P("tensor")
    if arch == "gpt2":
        return {
            "ln1": {"scale": P(), "bias": P()},
            "ln2": {"scale": P(), "bias": P()},
            "q": {"kernel": col, "bias": vec},
            "k": {"kernel": col, "bias": vec},
            "v": {"kernel": col, "bias": vec},
            "o": {"kernel": row, "bias": P()},
            "mlp": {
                "in": {"kernel": col, "bias": vec},
                "out": {"kernel": row, "bias": P()},
            },
        }
    return {
        "ln1": {"scale": P()},
        "ln2": {"scale": P()},
        "q": {"kernel": col},
        "k": {"kernel": col},
        "v": {"kernel": col},
        "o": {"kernel": row},
        "mlp": {
            "gate": {"kernel": col},
            "up": {"kernel": col},
            "down": {"kernel": row},
        },
    }


def gpt_param_sharding(mesh: Mesh, params: Params, arch: str = "gpt2") -> Params:
    """PartitionSpec tree for decoder LM params (megatron-style TP).

    The vocab dim shards only when it divides the tensor axis; otherwise the
    embedding/head replicate (correct either way — vocab sharding is a
    memory optimization, and odd vocabs like the 257-entry byte tokenizer
    must still serve)."""
    layer_spec = _gpt_layer_spec(arch)
    tp = mesh.shape.get("tensor", 1)
    vocab_divides = params["wte"].shape[0] % tp == 0
    spec: dict = {
        "wte": P("tensor", None) if vocab_divides else P(),
        "layers": [layer_spec for _ in params["layers"]],
        "ln_f": {k: P() for k in params["ln_f"]},
    }
    if "wpe" in params:
        spec["wpe"] = P()
    if "lm_head" in params:
        spec["lm_head"] = {"kernel": P(None, "tensor") if vocab_divides
                           else P()}
    return spec


def shard_params(mesh: Mesh, params: Params, spec_tree: Params) -> Params:
    """Place params on the mesh per a PartitionSpec tree."""
    return jax.tree.map(
        lambda arr, spec: jax.device_put(arr, NamedSharding(mesh, spec)),
        params,
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
