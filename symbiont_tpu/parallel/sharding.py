"""Sharding specs: how params and batches lay out on the mesh.

Design per the scaling-book recipe: pick a mesh, annotate shardings with
NamedSharding/PartitionSpec, let XLA insert the collectives. Nothing here
issues a collective by hand except ring attention (which needs the explicit
ppermute schedule).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Any


def replicate(mesh: Mesh, tree: Params) -> Params:
    """Fully replicate a pytree across the mesh (embedding models: weights are
    small; DP wants replicas)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard dim 0 (batch) over the data axis; everything else replicated."""
    return NamedSharding(mesh, P(axis))


def _gpt_layer_spec(arch: str) -> dict:
    """TP rules for one decoder layer: attention heads and MLP hidden shard on
    'tensor'; output projections shard the contracting dim so XLA reduces the
    partial sums with a psum over 'tensor'."""
    col = P(None, "tensor")  # [in, out] sharded on out
    row = P("tensor", None)  # [in, out] sharded on in  (contraction → psum)
    vec = P("tensor")
    if arch == "gpt2":
        return {
            "ln1": {"scale": P(), "bias": P()},
            "ln2": {"scale": P(), "bias": P()},
            "q": {"kernel": col, "bias": vec},
            "k": {"kernel": col, "bias": vec},
            "v": {"kernel": col, "bias": vec},
            "o": {"kernel": row, "bias": P()},
            "mlp": {
                "in": {"kernel": col, "bias": vec},
                "out": {"kernel": row, "bias": P()},
            },
        }
    return {
        "ln1": {"scale": P()},
        "ln2": {"scale": P()},
        "q": {"kernel": col},
        "k": {"kernel": col},
        "v": {"kernel": col},
        "o": {"kernel": row},
        "mlp": {
            "gate": {"kernel": col},
            "up": {"kernel": col},
            "down": {"kernel": row},
        },
    }


def gpt_param_sharding(mesh: Mesh, params: Params, arch: str = "gpt2") -> Params:
    """PartitionSpec tree for decoder LM params (megatron-style TP).

    The vocab dim shards only when it divides the tensor axis; otherwise the
    embedding/head replicate (correct either way — vocab sharding is a
    memory optimization, and odd vocabs like the 257-entry byte tokenizer
    must still serve)."""
    layer_spec = _gpt_layer_spec(arch)
    tp = mesh.shape.get("tensor", 1)
    vocab_divides = params["wte"].shape[0] % tp == 0
    spec: dict = {
        "wte": P("tensor", None) if vocab_divides else P(),
        "layers": [layer_spec for _ in params["layers"]],
        "ln_f": {k: P() for k in params["ln_f"]},
    }
    if "wpe" in params:
        spec["wpe"] = P()
    if "lm_head" in params:
        spec["lm_head"] = {"kernel": P(None, "tensor") if vocab_divides
                           else P()}
    return spec


def _is_quant(x) -> bool:
    from symbiont_tpu.models.quant import QuantTensor

    return isinstance(x, QuantTensor)


def shard_params(mesh: Mesh, params: Params, spec_tree: Params) -> Params:
    """Place params on the mesh per a PartitionSpec tree.

    QuantTensor leaves (models/quant.py int8/fp8 weights) shard too: the
    codes take the kernel's own spec, and the per-output-channel scale
    vector shards on the kernel's LAST axis entry — a col-sharded kernel
    P(None, 'tensor') keeps its scales co-resident with their channels
    (P('tensor')), a row-sharded kernel P('tensor', None) has unsharded
    output channels so the scales replicate. That co-residency is what lets
    `quantize=int8` compose with TP decode instead of falling back
    unquantized (ROADMAP item 1 / PR 7 gap)."""
    from symbiont_tpu.models.quant import QuantTensor

    def place(arr, spec):
        if isinstance(arr, QuantTensor):
            scale_spec = P(spec[-1]) if len(spec) else P()
            return QuantTensor(
                jax.device_put(arr.q, NamedSharding(mesh, spec)),
                jax.device_put(arr.scale, NamedSharding(mesh, scale_spec)))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree.map(
        place,
        params,
        spec_tree,
        is_leaf=lambda x: isinstance(x, P) or _is_quant(x),
    )


def corpus_topk(mesh: Mesh, corpus, query, n_valid, k: int,
                axis: str = "data"):
    """Corpus-sharded exact top-k: per-shard `lax.top_k` + global merge.

    `corpus` is [cap, D] row-sharded over `axis` (cap divisible by the axis
    size — VectorStore._capacity guarantees it), `query` a replicated [D]
    vector, `n_valid` the traced count of real rows. Each shard scores its
    own rows against the replicated query (bf16 on the MXU, f32 scores) and
    keeps its local top-k with GLOBAL row indices; the merge then top-ks the
    [n_shards × k] candidate set. Only k candidates per shard ever cross the
    interconnect instead of the full score vector — the term that keeps the
    10k-corpus p50 flat at 1M+ rows.

    Result-order identity with the single-device path (pinned in tests):
    `lax.top_k` breaks score ties by position, shards concatenate in
    global-row order, so the merged ordering is exactly the unsharded one.
    Trace-time only (call inside jit with the mesh's sharded operands)."""
    import jax.numpy as jnp

    from symbiont_tpu.parallel.compat import shard_map

    nd = mesh.shape[axis]
    cap = corpus.shape[0]
    if cap % nd:
        raise ValueError(f"corpus capacity {cap} not divisible by "
                         f"{axis}={nd}")
    rows = cap // nd
    k_local = min(k, rows)

    def local(c, q, nv):
        base = jax.lax.axis_index(axis) * rows
        scores = (c.astype(jnp.bfloat16) @ q.astype(jnp.bfloat16)
                  ).astype(jnp.float32)
        gidx = base + jnp.arange(rows)
        scores = jnp.where(gidx < nv, scores, -jnp.inf)
        s, li = jax.lax.top_k(scores, k_local)
        return s, gidx[li]

    cand_s, cand_i = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(None), P()),
        out_specs=(P(axis), P(axis)))(corpus, query, n_valid)
    merged_s, pos = jax.lax.top_k(cand_s, k)
    return merged_s, cand_i[pos]
