"""Context parallelism: sequence-sharded decoder training forward.

SURVEY.md §5.7 records that the reference hard-truncates every sequence to one
model's max length (reference: embedding_generator.rs:93-99) and has no
sequence parallelism of any kind. Here long-context LM *training* is
first-class: the batch's sequence dim shards over a mesh axis, every token
mixing op is local except attention, and attention is exact over the full
sequence via the ring schedule (parallel/ring_attention.py — K/V blocks rotate
over ICI with `ppermute` while a streaming softmax accumulates). Activation
memory per device is O(S/n); attention FLOPs stay exact, not windowed.

This is the training-side complement of the KV-cache decode path in
models/gpt.py: same params pytree, same layer math (`_ln`/`_rmsnorm`/`_rope`
are imported, not re-implemented), no cache — causality comes from the ring
step's global-position mask.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from symbiont_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from symbiont_tpu.models.gpt import (
    GPTConfig,
    _ln,
    _rmsnorm,
    block_nocache,
    qkv_proj,
)
from symbiont_tpu.parallel.ring_attention import ring_attention
from symbiont_tpu.parallel.ulysses import ulysses_attention

Params = Any


def _block_sp(layer, x, positions, cfg: GPTConfig, axis: str, attn_impl: str):
    """One decoder block with sequence-parallel attention; x: [B, S_loc, H]
    (local shard), positions: [B, S_loc] global token positions. Block
    scaffolding and QKV projection come from models/gpt (block_nocache /
    qkv_proj) — only the attention schedule is local to this module."""
    B, S, H = x.shape
    nh, nkv = cfg.num_heads, cfg.kv_heads

    def attn(h):
        q, k, v = qkv_proj(layer, h, positions, cfg)
        if attn_impl == "ulysses":
            # Ulysses re-shards heads over the axis, so K/V must be at full
            # head count first (the all-to-all splits the head dim)
            if nkv != nh:
                k = jnp.repeat(k, nh // nkv, axis=2)
                v = jnp.repeat(v, nh // nkv, axis=2)
            ctx = ulysses_attention(q, k, v, axis, causal=True).reshape(B, S, H)
        else:
            # GQA: K/V stay at nkv heads — the ring rotates the compact
            # blocks and expands to nh only at the local score computation
            ctx = ring_attention(q, k, v, axis, causal=True).reshape(B, S, H)
        return ctx @ layer["o"]["kernel"] + layer["o"].get("bias", 0)

    return block_nocache(layer, x, cfg, attn)


def gpt_forward_sp(
    params: Params,
    input_ids: jax.Array,  # [B, S] — S divisible by mesh.shape[axis]
    mesh: Mesh,
    cfg: GPTConfig,
    axis: str = "data",
    attn_impl: str = "ring",
) -> jax.Array:
    """Sequence-parallel training forward → logits [B, S, V] (sharded on S).

    Params replicate; activations shard on the sequence dim; the only
    cross-device traffic is the ring's K/V rotation. Equality with the
    KV-cache forward (models/gpt.py) is asserted in tests/test_parallel.py.
    """
    n = mesh.shape[axis]
    B, S = input_ids.shape
    if S % n != 0:
        raise ValueError(f"sequence length {S} not divisible by mesh axis "
                         f"{axis!r} size {n}")
    dtype = jnp.dtype(cfg.dtype)
    params = jax.tree.map(
        lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
        params)

    def local(params, ids_loc):  # ids_loc: [B, S/n]
        idx = jax.lax.axis_index(axis)
        S_loc = ids_loc.shape[1]
        positions = jnp.broadcast_to(
            idx * S_loc + jnp.arange(S_loc, dtype=jnp.int32), (B, S_loc))
        x = params["wte"][ids_loc]
        if cfg.arch == "gpt2":
            x = x + params["wpe"][positions]
        for layer in params["layers"]:
            x = _block_sp(layer, x, positions, cfg, axis, attn_impl)
        if cfg.arch == "gpt2":
            x = _ln(x, params["ln_f"], cfg.layer_norm_eps)
        else:
            x = _rmsnorm(x, params["ln_f"], cfg.layer_norm_eps)
        head = (params["wte"].T if cfg.tie_word_embeddings
                else params["lm_head"]["kernel"])
        return (x @ head).astype(jnp.float32)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis, None),
    )
    return fn(params, input_ids)


def lm_loss_sp(params: Params, batch: dict, cfg: GPTConfig, mesh: Mesh,
               axis: str = "data", attn_impl: str = "ring") -> jax.Array:
    """Next-token CE over a sequence-sharded forward. The shifted-target
    gather crosses shard boundaries; XLA inserts the halo exchange."""
    import optax

    ids = batch["ids"]
    mask = batch["mask"].astype(jnp.float32)
    logits = gpt_forward_sp(params, ids, mesh, cfg, axis=axis,
                            attn_impl=attn_impl)
    targets = ids[:, 1:]
    w = mask[:, 1:] * mask[:, :-1]
    ce = optax.softmax_cross_entropy_with_integer_labels(logits[:, :-1], targets)
    return (ce * w).sum() / jnp.maximum(w.sum(), 1.0)


def make_lm_train_step_sp(mesh: Mesh, cfg: GPTConfig, tx, axis: str = "data",
                          attn_impl: str = "ring"):
    """Build a jitted sequence-parallel LM train step bound to (mesh, axis).

    Complements trainer.lm_train_step: same TrainState/metrics contract, but
    activations shard over the sequence so contexts far beyond one device's
    HBM train exactly (ring attention, no approximation).
    """
    from symbiont_tpu.train.trainer import TrainState

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: dict):
        loss, grads = jax.value_and_grad(lm_loss_sp)(
            state.params, batch, cfg, mesh, axis, attn_impl)
        import optax

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (TrainState(new_params, opt_state, state.step + 1),
                {"loss": loss, "grad_norm": optax.global_norm(grads)})

    return step
