"""Ring attention: sequence-parallel exact attention over the mesh.

Long-context is a first-class capability here even though the reference
hard-truncates everything to one model's max length (reference:
services/preprocessing_service/src/embedding_generator.rs:93-99; SURVEY.md
§5.7). Design follows blockwise ring attention: the sequence is sharded over a
mesh axis, each device streams the K/V blocks of its peers around the ring with
`ppermute` while maintaining a numerically-stable streaming softmax
(flash-attention style running max/denominator), so attention over a sequence
of length S costs O(S/n) memory per device and the K/V transfer rides ICI.

Usage: call `ring_attention` *inside* `shard_map` with the sequence dim sharded
on `axis_name` (helper `ring_attention_sharded` wires this). Exactness is
tested against full attention on the 8-virtual-device CPU mesh
(tests/test_parallel.py).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from symbiont_tpu.parallel.compat import axis_size, pcast, shard_map


def ring_attention(
    q: jax.Array,  # [B, S_loc, NH, D] — local query block
    k: jax.Array,  # [B, S_loc, KVH, D] — local key block (KVH divides NH: GQA)
    v: jax.Array,  # [B, S_loc, KVH, D]
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over the full (sharded) sequence; call inside shard_map.

    GQA-aware: K/V may carry fewer heads than Q (KVH | NH). The compact KVH
    blocks are what rotates over the ring — expanding to NH happens only at
    the local score computation, so grouped-query models don't pay
    NH/KVH × the necessary ICI bandwidth."""
    n_dev = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S, NH, D = q.shape
    KVH = k.shape[2]
    if NH % KVH != 0:
        raise ValueError(f"query heads {NH} not divisible by KV heads {KVH}")
    rep = NH // KVH

    def expand(blk):  # [B, S, KVH, D] → [B, S, NH, D] (local, post-rotation)
        return jnp.repeat(blk, rep, axis=2) if rep > 1 else blk

    scale = 1.0 / math.sqrt(D)

    q32 = q.astype(jnp.float32)
    q_pos = idx * S + jnp.arange(S)  # global positions of local queries

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(s, carry):
        k_blk, v_blk, m, l, acc = carry
        # after s hops, we hold the block originally owned by (idx - s) mod n
        src = (idx - s) % n_dev
        kv_pos = src * S + jnp.arange(S)

        scores = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            expand(k_blk).astype(jnp.float32)) * scale
        if causal:
            mask = q_pos[None, None, :, None] >= kv_pos[None, None, None, :]
            scores = jnp.where(mask, scores, -jnp.inf)

        blk_max = jnp.max(scores, axis=-1)  # [B, NH, S]
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked blocks (all -inf): exp(-inf - finite) = 0 is fine,
        # but new_m could stay -inf early under causal; keep it, corrections
        # below use where() to avoid NaN.
        correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - new_m))
        probs = jnp.exp(scores - jnp.where(jnp.isneginf(new_m), 0.0, new_m)[..., None])
        probs = jnp.where(jnp.isneginf(scores), 0.0, probs)

        l = l * correction + probs.sum(axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", probs, expand(v_blk).astype(jnp.float32))

        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, new_m, l, acc

    # pcast to 'varying': mark the fresh accumulators as device-varying over
    # the ring axis so the fori_loop carry type is stable under shard_map's
    # varying-axis tracking.
    def vary(x):
        return pcast(x, axis_name, to="varying")

    m0 = vary(jnp.full((B, NH, S), -jnp.inf, jnp.float32))
    l0 = vary(jnp.zeros((B, NH, S), jnp.float32))
    acc0 = vary(jnp.zeros((B, NH, S, D), jnp.float32))
    *_, m, l, acc = jax.lax.fori_loop(0, n_dev, step, (k, v, m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, NH, S, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, S, NH, D]


def ring_attention_sharded(
    q: jax.Array,  # [B, S, NH, D] — full sequence (host view)
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "data",
    causal: bool = False,
) -> jax.Array:
    """Convenience wrapper: shard the sequence dim over `axis_name` and run
    ring attention; returns the full [B, S, NH, D] result."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
