"""Pipeline parallelism (GPipe schedule) for decoder-LM training.

SURVEY.md §2's parallelism table scoped PP out for the reference's model
sizes but required the mesh to keep a slot for it ("design mesh axes so PP
can be added"). This module fills that slot with a real implementation, the
TPU-idiomatic way: no scheduler process, no send/recv framework — the whole
schedule is ONE jitted SPMD program. Layers are stacked and sharded over a
`pipe` mesh axis (each device holds a contiguous stage of depth L/P);
microbatch activations flow stage-to-stage with `lax.ppermute` over ICI
inside a `lax.scan` over the GPipe timeline; `jax.grad` differentiates
straight through the collective, so the backward schedule falls out of the
forward's transpose instead of being hand-written.

Semantics are exact: the pipelined loss/step equals the plain
trainer.lm_train_step on the same batch (asserted in tests/test_parallel.py)
— microbatching changes the schedule, not the math, because each microbatch's
loss contributions are accumulated as (ce_sum, weight_sum) and normalized
once at the end.

Deliberate simplicity (documented, not hidden): embeddings and the LM head
replicate on every stage and run every tick with the results masked — at
these vocab/model sizes (SURVEY.md: nothing above TinyLlama-1.1B) the waste
is small and the program stays a single dense scan XLA can pipeline; a
head-sharded schedule is the upgrade path if the model zoo ever outgrows it.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from symbiont_tpu.parallel.compat import pcast, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from symbiont_tpu.models.gpt import (
    GPTConfig,
    _ln,
    _rmsnorm,
    block_nocache,
    qkv_proj,
)

Params = Any


def _block_dense(layer, x, positions, cfg: GPTConfig):
    """One decoder block, plain causal attention, no cache — the stage-local
    training forward. Block scaffolding and QKV projection come from
    models/gpt (block_nocache / qkv_proj); only the dense causal attention
    is local to this module."""
    import math

    B, S, H = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim

    def attn(h):
        q, k, v = qkv_proj(layer, h, positions, cfg)
        if nkv != nh:
            k = jnp.repeat(k, nh // nkv, axis=2)
            v = jnp.repeat(v, nh // nkv, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
        causal = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(causal[None, None], scores.astype(jnp.float32), -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, H)
        return ctx @ layer["o"]["kernel"] + layer["o"].get("bias", 0)

    return block_nocache(layer, x, cfg, attn)


# ------------------------------------------------------------------ params


def stack_layers(params: Params) -> Params:
    """Re-shape the per-layer param list into stacked arrays with a leading
    layer axis — the shape PP shards over `pipe` (and lax.scan consumes).
    The rest of the tree (embeddings, final norm, head) is passed through."""
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *params["layers"])
    return out


def shard_pp_params(mesh: Mesh, stacked: Params, axis: str = "pipe") -> Params:
    """Place stacked params on the mesh: layer stack split over the pipe
    axis (each device holds its stage's depth), everything else replicated."""
    n = mesh.shape[axis]
    L = jax.tree.leaves(stacked["layers"])[0].shape[0]
    if L % n != 0:
        raise ValueError(f"num_layers {L} not divisible by pipe axis size {n}")
    placed = {
        k: jax.tree.map(lambda a: jax.device_put(
            a, NamedSharding(mesh, P())), v)
        for k, v in stacked.items() if k != "layers"
    }
    placed["layers"] = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P(axis))),
        stacked["layers"])
    return placed


# ----------------------------------------------------------------- forward


def lm_loss_pp(params: Params, batch: dict, cfg: GPTConfig, mesh: Mesh,
               axis: str = "pipe", num_microbatches: int = 4) -> jax.Array:
    """Masked next-token CE through the GPipe schedule. `params` is the
    stacked form (stack_layers); batch["ids"/"mask"]: [B, S] with B
    divisible by num_microbatches."""
    n_stages = mesh.shape[axis]
    ids, mask = batch["ids"], batch["mask"]
    B, S = ids.shape
    M = num_microbatches
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    dtype = jnp.dtype(cfg.dtype)
    params = jax.tree.map(
        lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating)
        else a, params)
    mB = B // M

    def local(stage_layers, shared, ids, mask):
        # stage_layers: [L/P, ...] — this device's contiguous depth slice
        p = jax.lax.axis_index(axis)
        ids_m = ids.reshape(M, mB, S)
        mask_m = mask.reshape(M, mB, S)
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (mB, S))
        head = (shared["wte"].T if cfg.tie_word_embeddings
                else shared["lm_head"]["kernel"])

        def embed(mb_ids):
            x = shared["wte"][mb_ids]
            if cfg.arch == "gpt2":
                x = x + shared["wpe"][positions]
            return x.astype(dtype)

        def run_stage(x):
            def body(x, layer):
                return _block_dense(layer, x, positions, cfg), None
            return jax.lax.scan(body, x, stage_layers)[0]

        def micro_loss(x, mb_mask, mb_ids):
            if cfg.arch == "gpt2":
                x = _ln(x, shared["ln_f"], cfg.layer_norm_eps)
            else:
                x = _rmsnorm(x, shared["ln_f"], cfg.layer_norm_eps)
            logits = (x @ head).astype(jnp.float32)
            import optax

            m = mb_mask.astype(jnp.float32)
            w = m[:, 1:] * m[:, :-1]
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], mb_ids[:, 1:])
            return (ce * w).sum(), w.sum()

        def tick(carry, t):
            x, ce_acc, w_acc = carry
            # GPipe dataflow: stage p at tick t processes microbatch t-p.
            # Stage 0 injects a fresh microbatch; others use the activation
            # received last tick. Out-of-range ticks compute on stale data
            # and are masked out of the loss (their grads are exactly zero).
            feed = embed(ids_m[jnp.clip(t, 0, M - 1)])
            x = jnp.where(p == 0, feed, x)
            x = run_stage(x)
            m_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            ce, w = micro_loss(x, mask_m[m_idx], ids_m[m_idx])
            valid = ((p == n_stages - 1) & (t >= n_stages - 1)
                     ).astype(jnp.float32)
            ce_acc = ce_acc + valid * ce
            w_acc = w_acc + valid * w
            x = jax.lax.ppermute(
                x, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (x, ce_acc, w_acc), None

        x0 = jnp.zeros((mB, S, cfg.hidden_size), dtype)
        zero = jnp.zeros((), jnp.float32)  # strong-typed: scan carry must
        #                                    not drift from weak to strong
        # the carry becomes device-varying after the first tick (axis_index
        # select + ppermute), so the initial value must be marked varying too
        x0, zero_ce, zero_w = pcast((x0, zero, zero), (axis,),
                                            to="varying")
        (x, ce_acc, w_acc), _ = jax.lax.scan(
            tick, (x0, zero_ce, zero_w), jnp.arange(M + n_stages - 1))
        # only the last stage accumulated; psum replicates the totals
        ce_acc = jax.lax.psum(ce_acc, axis)
        w_acc = jax.lax.psum(w_acc, axis)
        return ce_acc / jnp.maximum(w_acc, 1.0)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(), P(), P()),
        out_specs=P(),
    )
    shared = {k: v for k, v in params.items() if k != "layers"}
    return fn(params["layers"], shared, ids, mask)


def make_lm_train_step_pp(mesh: Mesh, cfg: GPTConfig, tx, axis: str = "pipe",
                          num_microbatches: int = 4):
    """Jitted pipeline-parallel LM train step bound to (mesh, axis).

    Same TrainState/metrics contract as trainer.lm_train_step; state params
    must be the stacked+sharded form (stack_layers → shard_pp_params, or
    make_pp_train_state). The backward schedule is jax.grad's transpose of
    the forward scan — reverse ppermutes included."""
    from symbiont_tpu.train.trainer import TrainState

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: dict):
        import optax

        loss, grads = jax.value_and_grad(lm_loss_pp)(
            state.params, batch, cfg, mesh, axis, num_microbatches)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (TrainState(new_params, opt_state, state.step + 1),
                {"loss": loss, "grad_norm": optax.global_norm(grads)})

    return step


def make_pp_train_state(mesh: Mesh, params: Params, learning_rate: float = 3e-4,
                        axis: str = "pipe"):
    """TrainState over stacked+sharded params (optimizer state inherits the
    same shardings via tx.init on the placed arrays)."""
    import optax

    from symbiont_tpu.train.trainer import TrainState

    placed = shard_pp_params(mesh, stack_layers(params), axis=axis)
    from symbiont_tpu.train.trainer import _adamw

    tx = _adamw(learning_rate)  # same optimizer as make_lm_train_state —
    #                             the PP and plain steps must stay in lockstep
    return TrainState(placed, tx.init(placed),
                      jnp.zeros((), jnp.int32)), tx
