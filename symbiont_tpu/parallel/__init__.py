"""Device-plane parallelism (ICI): mesh, sharding specs, ring attention.

The reference has NO device parallelism of any kind — single candle device,
serial batch-8 loop (reference:
services/preprocessing_service/src/embedding_generator.rs:146-216), and its
only "distributed" layer is NATS pub/sub between single-instance services
(SURVEY.md §2 parallelism inventory). This package is the TPU-native design
that replaces that absence:

mesh       : named device meshes (axes: data, tensor) over real TPU slices or
             the 8-virtual-device CPU backend used in tests
sharding   : NamedSharding rules — DP batch sharding for embedding, TP rules
             for decoder LM params (heads / MLP hidden on 'tensor')
ring_attention : sequence-parallel blockwise attention via shard_map+ppermute
             for long-context (a first-class capability the reference lacks)
context    : sequence-parallel decoder LM *training* — the full train-time
             forward with activations sharded on the sequence dim and exact
             causal attention over the ring (gpt_forward_sp / lm_loss_sp /
             make_lm_train_step_sp)
ulysses    : the all-to-all sequence-parallel scheme — trade sequence shards
             for head shards, run dense attention, trade back (same exactness
             contract as ring; pick per workload)
pipeline   : GPipe pipeline parallelism — layer stages sharded over a 'pipe'
             axis, microbatch activations flowing via ppermute inside one
             jitted scan (lm_loss_pp / make_lm_train_step_pp /
             make_pp_train_state; exact vs the unsharded step)

XLA inserts the collectives (psum/all-gather/ppermute ride ICI); this package
only defines meshes and shardings — no hand-written NCCL analog (SURVEY.md §2
"Distributed communication backend").
"""

from symbiont_tpu.parallel.mesh import (
    build_mesh,
    init_distributed,
    local_device_count,
    mesh_from_config,
    parse_mesh_spec,
)
from symbiont_tpu.parallel.sharding import (
    batch_sharding,
    corpus_topk,
    gpt_param_sharding,
    replicate,
    shard_params,
)
from symbiont_tpu.parallel.context import (
    gpt_forward_sp,
    lm_loss_sp,
    make_lm_train_step_sp,
)
from symbiont_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_sharded,
)
from symbiont_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_sharded,
)
from symbiont_tpu.parallel.pipeline import (
    lm_loss_pp,
    make_lm_train_step_pp,
    make_pp_train_state,
)

__all__ = [
    "build_mesh",
    "init_distributed",
    "local_device_count",
    "batch_sharding",
    "replicate",
    "gpt_param_sharding",
    "shard_params",
    "gpt_forward_sp",
    "lm_loss_sp",
    "make_lm_train_step_sp",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "lm_loss_pp",
    "make_lm_train_step_pp",
    "make_pp_train_state",
]
