"""Mesh construction over whatever devices the runtime exposes.

Axes:
- "data"   — batch sharding (DP); embedding throughput scales on this axis.
- "tensor" — parameter sharding (TP) for decoder LMs too big for one chip.

PP/SP are deliberately *pluggable, not default*: the mesh helper accepts
arbitrary extra axes so a pipeline or sequence axis can be added without
touching call sites (SURVEY.md §2: PP "design mesh axes so PP can be added").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def local_device_count() -> int:
    return len(jax.devices())


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """Multi-host bring-up: one engine process per host in a TPU slice.

    Thin wrapper over `jax.distributed.initialize` — on TPU pods the runtime
    discovers coordinator/process topology itself, so all arguments are
    optional (pass them explicitly only for non-TPU backends or tests). After
    this, `jax.devices()` spans the whole slice and `build_mesh` meshes over
    it; XLA collectives ride ICI within a host block and DCN between hosts.
    Env override: SYMBIONT_COORDINATOR / SYMBIONT_NUM_PROCESSES /
    SYMBIONT_PROCESS_ID. Returns the global device count.

    Safe to call when already initialized (a second call is a no-op)."""
    import os

    coordinator = coordinator or os.environ.get("SYMBIONT_COORDINATOR")
    if num_processes is None and "SYMBIONT_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["SYMBIONT_NUM_PROCESSES"])
    if process_id is None and "SYMBIONT_PROCESS_ID" in os.environ:
        process_id = int(os.environ["SYMBIONT_PROCESS_ID"])
    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        if "already" not in str(e).lower():
            raise
    return len(jax.devices())


def build_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("data", "tensor"),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh.

    shape=None → all devices on the first axis, 1 on the rest (pure DP, the
    right default for the embedding models: MiniLM..e5-large all fit a single
    v5e chip's HBM; TP is for LMs).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = [n] + [1] * (len(axis_names) - 1)
    shape = list(shape)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))
