"""Mesh construction over whatever devices the runtime exposes.

Axes:
- "data"   — batch sharding (DP); embedding throughput scales on this axis.
- "tensor" — parameter sharding (TP) for decoder LMs too big for one chip.

PP/SP are deliberately *pluggable, not default*: the mesh helper accepts
arbitrary extra axes so a pipeline or sequence axis can be added without
touching call sites (SURVEY.md §2: PP "design mesh axes so PP can be added").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def local_device_count() -> int:
    return len(jax.devices())


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """Multi-host bring-up: one engine process per host in a TPU slice.

    Thin wrapper over `jax.distributed.initialize` — on TPU pods the runtime
    discovers coordinator/process topology itself, so all arguments are
    optional (pass them explicitly only for non-TPU backends or tests). After
    this, `jax.devices()` spans the whole slice and `build_mesh` meshes over
    it; XLA collectives ride ICI within a host block and DCN between hosts.
    Env override: SYMBIONT_COORDINATOR / SYMBIONT_NUM_PROCESSES /
    SYMBIONT_PROCESS_ID. Returns the global device count.

    Safe to call when already initialized (a second call is a no-op)."""
    import os

    coordinator = coordinator or os.environ.get("SYMBIONT_COORDINATOR")
    if num_processes is None and "SYMBIONT_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["SYMBIONT_NUM_PROCESSES"])
    if process_id is None and "SYMBIONT_PROCESS_ID" in os.environ:
        process_id = int(os.environ["SYMBIONT_PROCESS_ID"])
    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        if "already" not in str(e).lower():
            raise
    return len(jax.devices())


def build_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("data", "tensor"),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh.

    shape=None → all devices on the first axis, 1 on the rest (pure DP, the
    right default for the embedding models: MiniLM..e5-large all fit a single
    v5e chip's HBM; TP is for LMs).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = [n] + [1] * (len(axis_names) - 1)
    shape = list(shape)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def parse_mesh_spec(spec: str) -> list:
    """`"dp4xtp2"` → [4, 2] (also plain `"4x2"`, or `"8"` for pure DP).

    The human-facing mesh shorthand of the bench CLI's `--mesh` knob and
    docs/SCALING.md: `dp<N>` is the 'data' axis, `tp<N>` the 'tensor' axis,
    in that order. Kept here (not in bench/) so deployment tooling can share
    the exact same parse."""
    import re

    s = spec.strip().lower()
    m = re.fullmatch(r"dp(\d+)(?:xtp(\d+))?", s)
    if m:
        return [int(m.group(1)), int(m.group(2) or 1)]
    m = re.fullmatch(r"tp(\d+)", s)
    if m:
        return [1, int(m.group(1))]
    m = re.fullmatch(r"(\d+)(?:x(\d+))?", s)
    if m:
        return [int(m.group(1)), int(m.group(2) or 1)]
    raise ValueError(
        f"mesh spec {spec!r} not understood: use dpNxtpM, dpN, tpM, NxM or N")


def mesh_from_config(parallel_cfg) -> Mesh:
    """THE production mesh constructor (ROADMAP item 1): build the serving
    mesh purely from `ParallelConfig` — `mesh_shape` unset means all local
    devices on the 'data' axis, 1 on the rest. The runner calls this once at
    stack start and threads the result through TpuEngine, LmEngine, and the
    vector store; no caller ever hands a mesh in by hand to go multi-chip."""
    return build_mesh(parallel_cfg.mesh_shape,
                      tuple(parallel_cfg.axis_names))
