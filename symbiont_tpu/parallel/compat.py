"""jax version compatibility for the parallel plane.

The serving mesh is now load-bearing for the LIVE stack (runner builds it
at boot), so `parallel/` must import and run on every jax this project
meets: the newer toolchains where `shard_map`/`axis_size`/`pcast` are
top-level stable API, AND the 0.4.x line where shard_map lives in
`jax.experimental`, the in-collective axis size comes from
`jax.core.axis_frame`, and pcast does not exist (everything inside
shard_map is implicitly device-varying there, so it is a no-op).
"""

from __future__ import annotations

import jax

try:  # jax >= 0.5 promoted shard_map to the top-level API
    from jax import shard_map  # type: ignore  # noqa: F401
except ImportError:  # the 0.4.x toolchain keeps it in experimental
    from jax.experimental.shard_map import shard_map  # type: ignore # noqa: F401


def axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis, inside a collective context."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)  # 0.4.x: int (or frame w/ .size)
    return frame if isinstance(frame, int) else frame.size


def pcast(x, axis_name, to: str = "varying"):
    """Mark values device-varying over an axis (newer shard_map's explicit
    varying-manual-axes tracking). On 0.4.x shard_map every value already
    is, so the cast is the identity."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to=to)
    return x
