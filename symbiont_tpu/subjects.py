"""Bus subject table — the system's internal API surface.

Parity with the reference's eight NATS subjects (SURVEY.md §1-L3; producers and
consumers cited there). Unlike the reference, which hardcodes each subject
string inside each service (e.g. reference: services/api_service/src/main.rs:20-24),
these are one configurable table shared by every service and by the native C++
workers (exported through the generated header).

The reference's `data.processed_text.tokenized` subject is ORPHANED in v0.3.0 —
knowledge_graph_service subscribes (reference:
services/knowledge_graph_service/src/main.rs:9,201) but nothing publishes
(reference: CHANGELOG.md:57-60). This framework deliberately restores the
producer: our preprocessing service publishes it (SURVEY.md fact #3).
"""

from __future__ import annotations

# pipeline (fire-and-forget pub/sub)
TASKS_PERCEIVE_URL = "tasks.perceive.url"
DATA_RAW_TEXT_DISCOVERED = "data.raw_text.discovered"
DATA_TEXT_WITH_EMBEDDINGS = "data.text.with_embeddings"
DATA_PROCESSED_TEXT_TOKENIZED = "data.processed_text.tokenized"  # un-orphaned here
TASKS_GENERATION_TEXT = "tasks.generation.text"
EVENTS_TEXT_GENERATED = "events.text.generated"
# streaming deltas (our addition — SURVEY.md §7 hard part #5 "streaming
# tokens back out through NATS→SSE"); the final full message still rides
# EVENTS_TEXT_GENERATED for reference-era consumers
EVENTS_TEXT_GENERATED_PARTIAL = "events.text.generated.partial"
# generation cancellation (overload-protection plane): published by the API
# gateway when an SSE client that was following a task disconnects
# mid-generation; the text generator frees the task's decode row / closes
# its stream so a vanished reader can never pin a KV slot
TASKS_GENERATION_CANCEL = "tasks.generation.cancel"
# generation-session durability plane (resilience/genlog.py, docs/RESILIENCE.md
# "Durable generation sessions"): when a generator worker dies mid-stream
# (heartbeat verdict, exit, or drain-deadline SIGKILL), the process supervisor
# republishes its journal tails here as plain-JSON resume tasks
# {"task_id", "record", "attempt"}; generator replicas consume under the
# text-generator queue group, so exactly one survivor adopts each orphaned
# session and continues its token stream from `record`'s snapshot
TASKS_GENERATION_RESUME = "tasks.generation.resume"

# process-failure plane (resilience/procsup.py): every supervised runner
# process publishes a liveness heartbeat under `_sys.heartbeat.<role>`; the
# supervisor subscribes the wildcard and declares a worker HUNG (SIGKILL +
# restart) when its heartbeats stall — the liveness signal a SIGSTOPped or
# deadlocked process cannot fake, unlike an exit code. The `_` prefix keeps
# heartbeats out of durable-stream capture by convention.
SYS_HEARTBEAT = "_sys.heartbeat"

# elastic-autoscaler drain protocol (resilience/autoscale.py +
# resilience/procsup.py scale_role): the supervisor publishes
# `_sys.drain.<role>` to retire one replica gracefully — the runner stops
# pulling new durable deliveries (detaching its consumers so unacked work
# redelivers to the surviving group members), flushes its UpsertCoalescer,
# finishes in-flight work, publishes a final heartbeat with
# `draining: true`, and exits. The supervisor enforces a deadline: a hung
# drain is SIGKILLed, and durable redelivery still loses nothing.
SYS_DRAIN = "_sys.drain"

# fleet telemetry plane (obs/fleet.py): each supervised role publishes
# bounded, periodic metric-snapshot deltas and completed span records under
# these prefixes (+ ".<role>"); the FleetAggregator in the API-role process
# (and the ProcessSupervisor) subscribes the wildcards and merges them into
# the federated `GET /metrics` exposition, the stitched cross-process
# flight-recorder traces, and the `GET /api/fleet` roll-up. Same `_` prefix
# convention as heartbeats: telemetry never enters durable-stream capture
# and never competes with the data path.
SYS_TELEMETRY_METRICS = "_sys.telemetry.metrics"
SYS_TELEMETRY_SPANS = "_sys.telemetry.spans"

# request-reply (query path)
TASKS_EMBEDDING_FOR_QUERY = "tasks.embedding.for_query"
TASKS_SEARCH_SEMANTIC_REQUEST = "tasks.search.semantic.request"
# graph-augmented search (the reference's knowledge-graph limb, finally
# load-bearing end-to-end: entity extraction → graph upsert → THIS query
# surface): token-overlap document lookup over the graph store, served by
# knowledge_graph behind POST /api/search/graph
TASKS_SEARCH_GRAPH_REQUEST = "tasks.search.graph.request"
# vector-store point count (request-reply, served by vector_memory): the
# operational surface a multi-process deployment needs to verify zero-loss
# ingest from OUTSIDE the store-owning process (bench/load.py --multiproc)
TASKS_MEMORY_COUNT = "tasks.memory.count"

ALL_SUBJECTS = [
    TASKS_PERCEIVE_URL,
    DATA_RAW_TEXT_DISCOVERED,
    DATA_TEXT_WITH_EMBEDDINGS,
    DATA_PROCESSED_TEXT_TOKENIZED,
    TASKS_GENERATION_TEXT,
    EVENTS_TEXT_GENERATED,
    EVENTS_TEXT_GENERATED_PARTIAL,
    TASKS_GENERATION_CANCEL,
    TASKS_GENERATION_RESUME,
    TASKS_EMBEDDING_FOR_QUERY,
    TASKS_SEARCH_SEMANTIC_REQUEST,
    TASKS_SEARCH_GRAPH_REQUEST,
]

# engine plane (framework-internal, not part of the reference's wire surface):
# request-reply subjects fronting the TPU-owning engine process, so native C++
# worker shells stay thin (SURVEY.md §2 checklist item 4: "C++ worker talks to
# it over [RPC]"). Riding the bus instead of a separate RPC port means every
# engine op gets queue-group fan-in, trace headers, and micro-batching across
# all callers for free.
ENGINE_EMBED_BATCH = "engine.embed.batch"
ENGINE_EMBED_QUERY = "engine.embed.query"
ENGINE_RERANK = "engine.rerank"
ENGINE_GENERATE = "engine.generate"
ENGINE_VECTOR_UPSERT = "engine.vector.upsert"
ENGINE_VECTOR_SEARCH = "engine.vector.search"
# fused interactive query: embed + cosine top-k in ONE device program (served
# only when the engine process co-hosts the vector store; the api gateway
# falls back to the 2-hop embed→search orchestration otherwise)
ENGINE_QUERY_SEARCH = "engine.query.search"
ENGINE_GRAPH_SAVE = "engine.graph.save"
ENGINE_HEALTH = "engine.health"

# queue groups: the reference uses plain subscribe() with no queue groups, so a
# second replica would double-process every message (SURVEY.md §1-L3 notes).
# Every pipeline consumer here subscribes under a queue group so workers scale
# out horizontally.
QUEUE_PERCEPTION = "q.perception"
QUEUE_PREPROCESSING = "q.preprocessing"
QUEUE_VECTOR_MEMORY = "q.vector_memory"
QUEUE_KNOWLEDGE_GRAPH = "q.knowledge_graph"
QUEUE_TEXT_GENERATOR = "q.text_generator"
QUEUE_ENGINE = "q.engine"
