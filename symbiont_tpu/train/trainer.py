"""Sharded training steps.

Pure-function design: a TrainState pytree (params, opt_state, step) and a
step function `(state, batch, key) -> (state, metrics)`; sharding is applied
by placing the state/batch on the mesh (DP batch axis, TP param shards for
LMs) and jitting — XLA inserts the gradient psums (scaling-book recipe; no
hand-written collectives).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from symbiont_tpu.models import bert as bert_mod
from symbiont_tpu.models import gpt as gpt_mod

Params = Any


class TrainState(NamedTuple):
    params: Params
    opt_state: Any
    step: jax.Array


def _adamw(learning_rate: float, weight_decay: float = 0.01):
    return optax.adamw(learning_rate, weight_decay=weight_decay)


# ---------------------------------------------------------------- embedder


def make_embedder_train_state(params: Params, learning_rate: float = 1e-4
                              ) -> Tuple[TrainState, optax.GradientTransformation]:
    tx = _adamw(learning_rate)
    return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32)), tx


def contrastive_loss(params: Params, batch: dict, cfg: bert_mod.BertConfig,
                     temperature: float = 0.05) -> jax.Array:
    """InfoNCE with in-batch negatives over (query, positive) pairs —
    the standard sentence-embedding fine-tune (bge/e5 recipe)."""
    q = bert_mod.embed_sentences(params, batch["q_ids"], batch["q_mask"], cfg,
                                 normalize=True)
    p = bert_mod.embed_sentences(params, batch["p_ids"], batch["p_mask"], cfg,
                                 normalize=True)
    logits = (q @ p.T) / temperature  # [B, B]
    labels = jnp.arange(q.shape[0])
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


@partial(jax.jit, static_argnames=("cfg", "tx"), donate_argnums=(0,))
def contrastive_train_step(state: TrainState, batch: dict, cfg, tx
                           ) -> Tuple[TrainState, dict]:
    loss, grads = jax.value_and_grad(contrastive_loss)(state.params, batch, cfg)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    gnorm = optax.global_norm(grads)
    return (TrainState(params, opt_state, state.step + 1),
            {"loss": loss, "grad_norm": gnorm})


# ---------------------------------------------------------------------- lm


def make_lm_train_state(params: Params, learning_rate: float = 3e-4
                        ) -> Tuple[TrainState, optax.GradientTransformation]:
    tx = _adamw(learning_rate)
    return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32)), tx


def lm_loss(params: Params, batch: dict, cfg: gpt_mod.GPTConfig) -> jax.Array:
    """Next-token cross-entropy over [B, S] token batches (mask-weighted)."""
    import dataclasses

    ids = batch["ids"]  # [B, S]
    mask = batch["mask"].astype(jnp.float32)  # [B, S]
    B, S = ids.shape
    # the TRAINING forward always runs an unquantized cache: a serving
    # config with kv_quant=int8 would put quantize-on-append round() in the
    # backward path, whose zero gradient silently kills most K/V-kernel
    # gradients (measured: grad norm 22.4 → 4.0). The cache type follows
    # the instance, so this one replace() confines int8 KV to decode.
    if cfg.kv_quant != "none":
        cfg = dataclasses.replace(cfg, kv_quant="none")
    cache = gpt_mod.init_cache(cfg, B, S, jnp.dtype(cfg.dtype))
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    logits, _ = gpt_mod.forward(params, ids, cache, positions, cfg)
    targets = ids[:, 1:]
    w = mask[:, 1:] * mask[:, :-1]
    ce = optax.softmax_cross_entropy_with_integer_labels(logits[:, :-1], targets)
    return (ce * w).sum() / jnp.maximum(w.sum(), 1.0)


@partial(jax.jit, static_argnames=("cfg", "tx"), donate_argnums=(0,))
def lm_train_step(state: TrainState, batch: dict, cfg, tx
                  ) -> Tuple[TrainState, dict]:
    loss, grads = jax.value_and_grad(lm_loss)(state.params, batch, cfg)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return (TrainState(params, opt_state, state.step + 1),
            {"loss": loss, "grad_norm": optax.global_norm(grads)})


# ------------------------------------------------------------- sharded lm


def shard_lm_train_state(mesh, state: TrainState, arch: str) -> TrainState:
    """Place a TrainState on the mesh: params per the megatron TP spec
    (symbiont_tpu.parallel.sharding), opt-state mirrors params, step
    replicated. The batch goes on the 'data' axis (caller)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbiont_tpu.parallel.sharding import gpt_param_sharding

    spec = gpt_param_sharding(mesh, state.params, arch=arch)

    def put(tree, spec_tree):
        return jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            tree, spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    params = put(state.params, spec)
    # adamw state: (ScaleByAdamState(count, mu, nu), wd, ...) — mu/nu mirror
    # the param tree; count and scalars replicate.
    def put_opt(x):
        if isinstance(x, (jnp.ndarray, jax.Array)) and x.ndim == 0:
            return jax.device_put(x, NamedSharding(mesh, P()))
        return x

    opt_state = jax.tree.map(put_opt, state.opt_state)
    # mu/nu subtrees share the param structure; re-place them with the spec
    import optax as _optax

    def reshard_like_params(os):
        if isinstance(os, _optax.ScaleByAdamState):
            return _optax.ScaleByAdamState(
                count=jax.device_put(os.count, NamedSharding(mesh, P())),
                mu=put(os.mu, spec), nu=put(os.nu, spec))
        return os

    opt_state = tuple(reshard_like_params(os) for os in opt_state)
    step = jax.device_put(state.step, NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)
