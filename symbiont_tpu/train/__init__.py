"""Training — a capability the reference lacks entirely (SURVEY.md §5.4: "no
model training, so no checkpoints"; its only 'learning' is the Markov chain
rebuilt from one hardcoded sentence each boot).

trainer    : sharded train steps — contrastive (InfoNCE, in-batch negatives)
             fine-tuning for the embedding models, and next-token CE for the
             decoder LMs — jitted over the mesh with DP batch sharding and
             (for LMs) megatron TP param sharding
checkpoint : params/opt-state persistence so engine restarts skip
             reconversion (SURVEY.md §5.4 plan)
"""

from symbiont_tpu.train.trainer import (
    contrastive_train_step,
    lm_train_step,
    make_embedder_train_state,
    make_lm_train_state,
)

__all__ = [
    "contrastive_train_step",
    "lm_train_step",
    "make_embedder_train_state",
    "make_lm_train_state",
]
