"""Online LM fine-tuning over ingested text — the "evolving organism" loop.

The reference's entire learning story is an order-1 Markov chain retrained
from one hardcoded sentence at every boot (reference:
services/text_generator_service/src/main.rs:169-174). This framework already
trains the Markov backend continuously on every ingested document
(services/text_generator.py); this module gives the decoder-LM backend the
same property: ingested text accumulates into packed [B, S] token batches
and periodically takes a few AdamW steps (train/trainer.lm_train_step), after
which the updated parameters are swapped into the serving LmEngine — so what
the organism reads measurably changes what it says.

Design constraints honored:
- ONE static shape: all training batches are [batch_size, seq_len]; tokens
  are packed into a ring of rows (no per-text padding waste, no recompiles).
- `lm_train_step` donates its input state, so the trainer owns a private
  copy of the params from the moment of construction; the serving engine
  receives a fresh copy at each sync (LmEngine.update_params), never a
  buffer the next step will donate away.
- Crash-safe persistence via train/checkpoint.save_train_state (optional):
  a restarted stack resumes from the accumulated learning instead of
  reverting to the checkpoint it booted from.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)


class OnlineLmTrainer:
    """Owns a TrainState for the LmEngine's model and feeds it ingested text.

    Thread-safe: train_on_texts serializes on an internal lock (training is
    called from executor threads by the service layer)."""

    def __init__(self, lm, learning_rate: float = 1e-4, seq_len: int = 64,
                 batch_size: int = 8, state_path: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from symbiont_tpu.train import checkpoint as ckpt
        from symbiont_tpu.train.trainer import make_lm_train_state

        self.lm = lm
        self.cfg = lm.model_cfg
        self.seq_len = int(min(seq_len, self.cfg.max_position_embeddings))
        self.batch_size = int(batch_size)
        self.state_path = state_path
        self._lock = threading.Lock()
        # token stream carried between passes: text beyond what one pass
        # consumes is trained later, up to MAX_PENDING_BATCHES of backlog
        # (beyond that, oldest tokens drop and stats["tokens_dropped"] counts)
        self._stream: list = []
        self.stats = {"train_steps": 0, "train_docs": 0, "last_loss": None,
                      "param_syncs": 0, "batches_trained": 0,
                      "tokens_pending": 0, "tokens_dropped": 0}

        # private copy: lm_train_step donates state, so training must never
        # share buffers with the serving engine's live params. Master
        # weights train in f32 regardless of the serving dtype — the engine
        # stores params at model dtype (bf16) since r5, and optimizing bf16
        # masters directly would lose update precision.
        #
        # ADVICE r5: when the engine booted from a real checkpoint and no
        # saved train state will be restored below, widening the engine's
        # bf16-rounded params would bake a one-time precision loss into the
        # masters — reload the ORIGINAL pre-cast checkpoint instead.
        params = None
        resuming = bool(state_path and ckpt.train_state_exists(state_path))
        model_dir = getattr(lm.config, "model_dir", None)
        if not resuming and model_dir:
            try:
                from symbiont_tpu.models.convert import load_gpt_model

                ck_params, _ = load_gpt_model(model_dir)
                params = jax.tree.map(
                    lambda a: (jnp.asarray(a, dtype=jnp.float32)
                               if jnp.issubdtype(np.asarray(a).dtype,
                                                 np.floating)
                               else jnp.asarray(a)), ck_params)
                log.info("online LM masters initialized from the pre-cast "
                         "checkpoint at %s", model_dir)
            except Exception:
                log.exception(
                    "could not reload the checkpoint at %s for f32 masters; "
                    "falling back to the engine's (bf16-rounded) params",
                    model_dir)
        if params is None:
            from symbiont_tpu.models import quant as quant_mod

            def widen(a):
                # a quantized engine (lm.quantize=int8/fp8) serves
                # QuantTensor leaves — masters must train on their f32
                # DEQUANTIZED values, not on raw int8 codes (grad would
                # reject integer inputs outright)
                if quant_mod.is_quantized(a):
                    return a.dequantize(jnp.float32)
                return (jnp.array(a, dtype=jnp.float32, copy=True)
                        if jnp.issubdtype(a.dtype, jnp.floating)
                        else jnp.copy(a))

            params = jax.tree.map(widen, lm.params,
                                  is_leaf=quant_mod.is_quantized)
        self.state, self._tx = make_lm_train_state(params, learning_rate)
        if resuming:  # one consistent answer with the masters-init decision
            try:
                self.state, meta = ckpt.load_train_state(state_path, self.state)
                self.stats["train_steps"] = int(meta.get("steps", 0))
                log.info("online LM train state restored from %s (step %s)",
                         state_path, self.stats["train_steps"])
                self._sync_engine()
            except ValueError as e:
                log.warning("online LM train state at %s does not match the "
                            "current model (%s); starting fresh", state_path, e)

    # ----------------------------------------------------------------- data

    # a single document is capped at this many tokens per encode — bounds the
    # host memory a pathological page can pin; a crawl-scale article fits
    _DOC_TOKEN_CAP = 1 << 18

    # one training pass consumes at most this many batches; the remainder of
    # the token stream carries over to the next pass (bounds pass latency so
    # one giant ingest burst can't monopolize the device)
    MAX_BATCHES_PER_PASS = 16

    # the carried stream is bounded too: when ingest sustainedly outruns
    # training throughput, tokens past this many batches' worth are dropped
    # OLDEST-first (counted in stats) — recent text wins, host memory stays
    # flat. MAX_BATCHES_PER_PASS bounds pass latency; this bounds backlog.
    MAX_PENDING_BATCHES = 64

    def _take_batches(self, texts: Sequence[str]):
        """Tokenize texts (BOS-separated) into the carried token stream,
        then drain as many full [batch_size, seq_len] batches as available
        (≤ MAX_BATCHES_PER_PASS). Leftover tokens stay in the stream for the
        NEXT pass, bounded at MAX_PENDING_BATCHES batches' worth — past that,
        oldest tokens drop (counted in stats["tokens_dropped"]). A stream too
        short for one full batch is cycled to fill it (short corpora still
        train)."""
        import jax.numpy as jnp

        tok = self.lm.tokenizer
        bos = getattr(tok, "bos_id", 0)
        for t in texts:
            ids = tok.encode(t, self._DOC_TOKEN_CAP)
            if ids:
                self._stream.extend(ids if ids[0] == bos else [bos] + ids)
        need = self.batch_size * self.seq_len
        cap = need * self.MAX_PENDING_BATCHES
        if len(self._stream) > cap:
            drop = len(self._stream) - cap
            del self._stream[:drop]  # oldest first: recent context wins
            self.stats["tokens_dropped"] += drop
            log.warning("online LM backlog over %d tokens; dropped %d oldest",
                        cap, drop)
        chunks: list = []
        while len(self._stream) >= need and len(chunks) < self.MAX_BATCHES_PER_PASS:
            chunks.append(self._stream[:need])
            del self._stream[:need]
        if not chunks:
            if len(self._stream) < 2:  # nothing to learn from
                return []
            reps = -(-need // len(self._stream))
            chunks = [(self._stream * reps)[:need]]
            self._stream = []
        out = []
        for chunk in chunks:
            ids = jnp.asarray(np.asarray(chunk, np.int32).reshape(
                self.batch_size, self.seq_len))
            out.append({"ids": ids, "mask": jnp.ones_like(ids)})
        self.stats["tokens_pending"] = len(self._stream)
        return out

    # ---------------------------------------------------------------- train

    def train_on_texts(self, texts: Sequence[str], steps: int = 1) -> dict:
        """Run `steps` optimizer steps over each drained batch, then swap
        the updated params into the serving engine. Returns metrics
        including the last step's loss."""
        import jax

        from symbiont_tpu.train.trainer import lm_train_step

        with self._lock:
            batches = self._take_batches(texts)
            if not batches:
                return {"loss": None, "steps": 0}
            loss = None
            n_steps = 0
            for batch in batches:
                for _ in range(max(1, int(steps))):
                    self.state, metrics = lm_train_step(self.state, batch,
                                                        self.cfg, self._tx)
                    loss = metrics["loss"]
                    n_steps += 1
            loss = float(jax.block_until_ready(loss))
            self.stats["train_steps"] += n_steps
            self.stats["train_docs"] += len(texts)
            self.stats["batches_trained"] += len(batches)
            self.stats["last_loss"] = loss
            self._sync_engine()
            if self.state_path:
                self._save()
        return {"loss": loss, "steps": n_steps, "batches": len(batches)}

    def _sync_engine(self) -> None:
        """Push a COPY of the trained params to the serving engine — the
        trainer's own buffers will be donated by the next step."""
        import jax
        import jax.numpy as jnp

        self.lm.update_params(jax.tree.map(jnp.copy, self.state.params))
        self.stats["param_syncs"] += 1

    def _save(self) -> None:
        from symbiont_tpu.train import checkpoint as ckpt

        try:
            ckpt.save_train_state(self.state_path, self.state,
                                  meta={"steps": self.stats["train_steps"]})
        except OSError:
            log.exception("online LM train-state save failed; continuing")
