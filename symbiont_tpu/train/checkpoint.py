"""Parameter/state checkpointing (SURVEY.md §5.4 plan).

The reference re-downloads + re-converts model weights at every preprocessing
service boot (reference: embedding_generator.rs:25-58) and rebuilds its Markov
state from a constant (text_generator_service/src/main.rs:169-173). Here
converted JAX params are saved once and memory-mapped back on restart, and the
Markov state persists via its to_state/from_state hooks.

Format: a directory with a flat .npz of leaves + a JSON treedef — dependency-
free and mmap-friendly. (orbax is available in the image; this avoids its
async machinery for what is a cold-path save/restore.)
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import numpy as np

Params = Any

_SEP = "\x1f"  # unit separator — safe key joiner


def _flatten(tree: Params, prefix: str = "") -> dict:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}{_SEP}"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}#{i}{_SEP}"))
    else:
        out[prefix.rstrip(_SEP)] = np.asarray(tree)
    return out


def _shape_of(tree: Params) -> Any:
    if isinstance(tree, dict):
        return {k: _shape_of(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_shape_of(v) for v in tree]
    return None  # leaf marker


def _unflatten(shape: Any, flat: dict, prefix: str = "") -> Params:
    if isinstance(shape, dict):
        return {k: _unflatten(v, flat, f"{prefix}{k}{_SEP}") for k, v in shape.items()}
    if isinstance(shape, list):
        return [_unflatten(v, flat, f"{prefix}#{i}{_SEP}")
                for i, v in enumerate(shape)]
    return flat[prefix.rstrip(_SEP)]


def save_params(path: str | Path, params: Params, meta: Optional[dict] = None) -> None:
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    flat = _flatten(params)
    np.savez(path / "params.npz", **flat)
    (path / "tree.json").write_text(json.dumps(
        {"tree": _shape_of(params), "meta": meta or {}}))


def load_params(path: str | Path) -> tuple[Params, dict]:
    path = Path(path)
    spec = json.loads((path / "tree.json").read_text())
    with np.load(path / "params.npz") as npz:
        flat = {k: npz[k] for k in npz.files}
    return _unflatten(spec["tree"], flat), spec.get("meta", {})


def exists(path: str | Path) -> bool:
    path = Path(path)
    return (path / "params.npz").exists() and (path / "tree.json").exists()


def save_train_state(path: str | Path, state, meta: Optional[dict] = None) -> None:
    """Full training-state checkpoint (params + optimizer state + step) for
    resume — the §5.4 capability the reference has no training to need.
    Optax states are arbitrary pytrees (NamedTuples inside), so leaves are
    saved in jax.tree order and restored into a caller-built template of the
    same structure (load_train_state)."""
    import os

    import jax

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    leaves = [np.asarray(l) for l in jax.tree.leaves(state)]
    # atomic: write-then-replace, so a crash mid-save never destroys the
    # previous good checkpoint (meta last — its presence implies a whole npz)
    tmp = path / "train_state.npz.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **{f"leaf_{i}": l for i, l in enumerate(leaves)})
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path / "train_state.npz")
    tmp_meta = path / "train_meta.json.tmp"
    with open(tmp_meta, "w") as f:
        f.write(json.dumps({
            "n_leaves": len(leaves),
            "shapes": [list(l.shape) for l in leaves],
            "dtypes": [str(l.dtype) for l in leaves],
            "meta": meta or {}}))
        f.flush()
        os.fsync(f.fileno())  # rename must not outlive the data
    os.replace(tmp_meta, path / "train_meta.json")


def load_train_state(path: str | Path, template):
    """Restore a train state saved by save_train_state into `template`'s
    structure (build it with the same make_*_train_state call). Returns
    (state, meta)."""
    import jax

    path = Path(path)
    spec = json.loads((path / "train_meta.json").read_text())
    with np.load(path / "train_state.npz") as npz:
        leaves = [npz[f"leaf_{i}"] for i in range(spec["n_leaves"])]
    structure = jax.tree.structure(template)
    if structure.num_leaves != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, template has "
            f"{structure.num_leaves} — model/optimizer config mismatch")
    # per-leaf shape check: equal leaf counts with different geometry must
    # fail HERE with a clear error, not later as an XLA shape error
    for i, (leaf, tmpl) in enumerate(zip(leaves, jax.tree.leaves(template))):
        t_shape = tuple(np.shape(tmpl))
        if tuple(leaf.shape) != t_shape:
            raise ValueError(
                f"leaf {i}: checkpoint shape {tuple(leaf.shape)} != template "
                f"shape {t_shape} — model/optimizer config mismatch")
    return jax.tree.unflatten(structure, leaves), spec.get("meta", {})


def train_state_exists(path: str | Path) -> bool:
    path = Path(path)
    return ((path / "train_state.npz").exists()
            and (path / "train_meta.json").exists())
