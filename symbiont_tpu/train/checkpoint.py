"""Parameter/state checkpointing (SURVEY.md §5.4 plan).

The reference re-downloads + re-converts model weights at every preprocessing
service boot (reference: embedding_generator.rs:25-58) and rebuilds its Markov
state from a constant (text_generator_service/src/main.rs:169-173). Here
converted JAX params are saved once and memory-mapped back on restart, and the
Markov state persists via its to_state/from_state hooks.

Format: a directory with a flat .npz of leaves + a JSON treedef — dependency-
free and mmap-friendly. (orbax is available in the image; this avoids its
async machinery for what is a cold-path save/restore.)
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import numpy as np

Params = Any

_SEP = "\x1f"  # unit separator — safe key joiner


def _flatten(tree: Params, prefix: str = "") -> dict:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}{_SEP}"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}#{i}{_SEP}"))
    else:
        out[prefix.rstrip(_SEP)] = np.asarray(tree)
    return out


def _shape_of(tree: Params) -> Any:
    if isinstance(tree, dict):
        return {k: _shape_of(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_shape_of(v) for v in tree]
    return None  # leaf marker


def _unflatten(shape: Any, flat: dict, prefix: str = "") -> Params:
    if isinstance(shape, dict):
        return {k: _unflatten(v, flat, f"{prefix}{k}{_SEP}") for k, v in shape.items()}
    if isinstance(shape, list):
        return [_unflatten(v, flat, f"{prefix}#{i}{_SEP}")
                for i, v in enumerate(shape)]
    return flat[prefix.rstrip(_SEP)]


def save_params(path: str | Path, params: Params, meta: Optional[dict] = None) -> None:
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    flat = _flatten(params)
    np.savez(path / "params.npz", **flat)
    (path / "tree.json").write_text(json.dumps(
        {"tree": _shape_of(params), "meta": meta or {}}))


def load_params(path: str | Path) -> tuple[Params, dict]:
    path = Path(path)
    spec = json.loads((path / "tree.json").read_text())
    with np.load(path / "params.npz") as npz:
        flat = {k: npz[k] for k in npz.files}
    return _unflatten(spec["tree"], flat), spec.get("meta", {})


def exists(path: str | Path) -> bool:
    path = Path(path)
    return (path / "params.npz").exists() and (path / "tree.json").exists()
