"""Service skeleton: the subscribe-dispatch loop every worker shares.

Mirrors the reference's per-service main-loop shape (subscribe →
`while let Some(msg) = sub.next().await` → spawn handler; e.g. reference:
services/perception_service/src/main.rs:172-247) with the flaws fixed that
SURVEY.md §5.2/§5.3 documents:

- bounded concurrency (semaphore) instead of unbounded tokio::spawn;
- queue-group subscriptions so replicas shard work instead of duplicating it;
- handler failures are counted + logged with trace context, never kill the
  loop;
- (resilience plane) per-handler TIMEOUT — a hung handler is cancelled, so
  it can never pin a semaphore slot, and its durable delivery stays unacked
  for redelivery — plus in-process RETRY with jittered exponential backoff
  for transient failures, both configurable via ResilienceConfig /
  `apply_resilience()`;
- dispatch loops are SUPERVISED (resilience/supervisor.py): a crashed loop
  restarts with backoff instead of dying unlogged.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
from typing import Awaitable, Callable, Optional

from symbiont_tpu.bus.core import Msg
from symbiont_tpu.resilience import admission, faults
from symbiont_tpu.resilience.supervisor import supervise
from symbiont_tpu.utils.retry import jittered
from symbiont_tpu.utils.telemetry import metrics, span

log = logging.getLogger(__name__)

Handler = Callable[[Msg], Awaitable[None]]


class HandlerTimeout(Exception):
    """The handler-deadline sentinel: raised by _attempt ONLY when OUR
    wait_for cancelled the handler. A TimeoutError raised by the handler's
    own code (a bus request timeout, a socket read timeout — and on 3.11+
    asyncio.TimeoutError IS builtin TimeoutError) is an ordinary failure:
    it must hit the retry/accounting path, not masquerade as the
    deadline."""


class Service:
    name = "service"

    def __init__(self, bus, max_concurrency: int = 32):
        self.bus = bus
        self._sem = asyncio.Semaphore(max_concurrency)
        self._tasks: set = set()
        self._subs: list = []
        self._loops: list = []
        self._running = False
        # resilience knobs (ResilienceConfig defaults; see apply_resilience)
        self.handler_timeout_s = 0.0  # 0 disables the timeout
        self.handler_retries = 0
        self.handler_backoff_base_s = 0.05
        self.handler_backoff_max_s = 2.0
        self.supervisor_backoff_base_s = 0.5
        self.supervisor_backoff_max_s = 30.0
        self._rng = random.Random()  # jitter source; seedable in tests

    def apply_resilience(self, cfg) -> None:
        """Adopt a ResilienceConfig (config.py). Called by the runner on
        every hosted service; individual services may override fields after
        (per-service tuning)."""
        self.handler_timeout_s = cfg.handler_timeout_s
        self.handler_retries = cfg.handler_retries
        self.handler_backoff_base_s = cfg.handler_backoff_base_s
        self.handler_backoff_max_s = cfg.handler_backoff_max_s
        self.supervisor_backoff_base_s = cfg.supervisor_backoff_base_s
        self.supervisor_backoff_max_s = cfg.supervisor_backoff_max_s

    async def start(self) -> None:
        self._running = True
        await self._setup()

    async def _setup(self) -> None:  # override: create subscriptions
        raise NotImplementedError

    async def _subscribe_loop(self, subject: str, handler: Handler,
                              queue: Optional[str] = None,
                              durable_stream: Optional[str] = None) -> None:
        """Dispatch loop. With `durable_stream` (and a bus that supports it),
        consumption is at-least-once: the delivery is acked only after the
        handler returns, so a crash mid-handler redelivers (SURVEY.md §5.3 —
        ack-after-durable, the stance the reference's wait=true upserts take
        at the storage layer but its bus never did)."""
        durable = (durable_stream is not None and queue is not None
                   and hasattr(self.bus, "durable_subscribe"))
        if durable:
            sub = await self.bus.durable_subscribe(durable_stream, queue,
                                                   filter_subject=subject)
        else:
            sub = await self.bus.subscribe(subject, queue=queue)
        self._subs.append(sub)

        async def loop() -> None:
            async for msg in sub:
                await self._sem.acquire()
                task = asyncio.create_task(
                    self._run_handler(subject, handler, msg, ack=durable))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)

        # supervised: an exception escaping the loop body restarts it with
        # backoff (same still-open subscription) instead of silently ending
        # consumption for the life of the process
        t = asyncio.create_task(
            supervise(loop, name=f"{self.name}:{subject}",
                      backoff_base_s=self.supervisor_backoff_base_s,
                      backoff_max_s=self.supervisor_backoff_max_s,
                      labels={"service": self.name},
                      still_wanted=lambda: self._running,
                      rng=self._rng),
            name=f"{self.name}:{subject}")
        self._loops.append(t)

    async def _drop_expired(self, subject: str, msg: Msg,
                            ack: bool) -> bool:
        """Deadline propagation (overload-protection plane): a message whose
        X-Symbiont-Deadline has passed is dropped BEFORE the handler runs —
        the caller already gave up, so doing the work only adds load at the
        worst time. Counted as `admission.expired{service}`; the durable
        delivery is ACKED (expiry is not a handler failure: it must not
        redeliver and must never quarantine as poison)."""
        if not admission.expired(msg.headers):
            return False
        metrics.inc("admission.expired",
                    labels={"service": self.name, "subject": subject})
        log.info("%s: dropping expired work on %s (deadline passed "
                 "%.0fms ago)", self.name, subject,
                 -(admission.remaining_ms(msg.headers) or 0.0))
        if ack:
            await self.bus.ack(msg)
        return True

    async def _run_handler(self, subject: str, handler: Handler, msg: Msg,
                           ack: bool = False) -> None:
        try:
            metrics.inc("bus.consumed",
                        labels={"service": self.name, "subject": subject})
            if await self._drop_expired(subject, msg, ack):
                return
            attempts = 1 + max(0, self.handler_retries)
            delay = self.handler_backoff_base_s
            for attempt in range(attempts):
                try:
                    await self._attempt(subject, handler, msg)
                except HandlerTimeout:
                    # the handler was CANCELLED at the deadline: the slot is
                    # free again, and (durable) the unacked delivery will
                    # redeliver after ack_wait — no in-process retry of a
                    # side effect whose state is unknown
                    metrics.inc("bus.handler_timeout",
                                labels={"service": self.name,
                                        "subject": subject})
                    log.warning(
                        "%s: handler for %s timed out after %.1fs and was "
                        "cancelled%s", self.name, subject,
                        self.handler_timeout_s,
                        " (unacked: will redeliver)" if ack else "")
                    return
                except asyncio.CancelledError:
                    raise
                except Exception:
                    metrics.inc("bus.failed",
                                labels={"service": self.name,
                                        "subject": subject})
                    log.exception("%s: handler failed for %s (attempt %d/%d)",
                                  self.name, subject, attempt + 1, attempts)
                    if attempt + 1 >= attempts:
                        return  # durable: stays unacked -> redelivery/DLQ
                    metrics.inc("bus.handler_retries",
                                labels={"service": self.name,
                                        "subject": subject})
                    # full-jitter exponential backoff between attempts
                    await asyncio.sleep(jittered(delay, self._rng))
                    delay = min(delay * 2, self.handler_backoff_max_s)
                    # the deadline may have passed during the backoff: a
                    # retry of expired work is load with no beneficiary
                    if await self._drop_expired(subject, msg, ack):
                        return
                    continue
                if ack:
                    # ack-after-success: a failed handler leaves the message
                    # unacked for redelivery
                    await self.bus.ack(msg)
                return
        finally:
            self._sem.release()

    async def _attempt(self, subject: str, handler: Handler, msg: Msg) -> None:
        """One handler invocation under its span, bounded by the handler
        timeout (the fault seam and any injected hang live INSIDE the
        timeout window, so chaos can prove the cancellation)."""
        with span(f"{self.name}.handle", msg.headers, subject=subject) as sp:
            # hand the handler a PRIVATE message bound to this handler
            # span's context: the inproc bus shares one Msg (and one
            # headers dict) across all subscribers, so rebinding a copy
            # — never mutating the original — is what lets every
            # downstream publish link to this span without racing a
            # sibling subscriber's handler (obs trace model; the ack
            # in _run_handler still uses the ORIGINAL msg, whose transport
            # headers the copy merge also preserves)
            hmsg = dataclasses.replace(
                msg, headers={**(msg.headers or {}), **sp.headers})

            async def invoke() -> None:
                plan = faults.active_plan()
                if plan is not None:
                    await plan.async_fault("handler",
                                           f"{self.name}:{subject}")
                await handler(hmsg)

            if self.handler_timeout_s > 0:
                fut = asyncio.ensure_future(invoke())
                try:
                    await asyncio.wait_for(fut, self.handler_timeout_s)
                except asyncio.TimeoutError:
                    if fut.cancelled():
                        # OUR deadline fired (wait_for cancelled the
                        # handler) — not a TimeoutError the handler raised
                        raise HandlerTimeout() from None
                    raise  # the handler's own timeout: a normal failure
            else:
                await invoke()

    async def drain(self) -> None:
        """Stop pulling NEW work, let everything already here land — the
        scale-in half of the drain protocol (resilience/autoscale.py).

        Closing a durable subscription DETACHES the consumer (TcpBus sends
        UNSUB and forgets it, so a reconnect never re-attaches): deliveries
        this worker pulled but never acked redeliver after `ack_wait` to
        the surviving queue-group members. The close sentinel lands BEHIND
        any locally-queued deliveries, so the dispatch loop runs the
        backlog to completion before exiting — those handlers' acks
        (including coalesced ack-after-flush waits, which the subclass
        drain() overrides switch to immediate-flush first) release
        normally.

        Request-reply subscriptions close the same way: they are
        at-most-once hops with no redelivery, so the loss window must be
        the one UNSUB round-trip (deliveries racing the close), never the
        locally-queued backlog — a storm's worth of requests already
        routed to this member is dispatched and ANSWERED below before the
        loops end, instead of being dropped into caller timeouts the way
        a plain stop()'s loop-cancel would. The supervisor-side deadline,
        not this method, is the bound on a drain that hangs."""
        self._running = False
        for s in self._subs:
            s.close()
        if self._loops:
            # NO cancel: each loop dispatches its queued backlog, then
            # ends on the close sentinel (which close() enqueues BEHIND
            # the backlog); supervise exits on the clean return
            done, pending = await asyncio.wait(self._loops, timeout=30.0)
            for t in pending:
                t.cancel()
            await asyncio.gather(*self._loops, return_exceptions=True)
        if self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)
        self._loops.clear()
        self._subs.clear()

    async def stop(self) -> None:
        self._running = False
        for s in self._subs:
            s.close()
        for t in self._loops:
            t.cancel()
        if self._loops:
            # await the cancellations: a fire-and-forget cancel leaves
            # "Task was destroyed but it is pending" warnings (and live
            # supervisor sleeps) behind on interpreter shutdown
            await asyncio.gather(*self._loops, return_exceptions=True)
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._loops.clear()
        self._subs.clear()
