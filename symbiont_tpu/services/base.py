"""Service skeleton: the subscribe-dispatch loop every worker shares.

Mirrors the reference's per-service main-loop shape (subscribe →
`while let Some(msg) = sub.next().await` → spawn handler; e.g. reference:
services/perception_service/src/main.rs:172-247) with the two flaws fixed
that SURVEY.md §5.2/§5.3 documents:

- bounded concurrency (semaphore) instead of unbounded tokio::spawn;
- queue-group subscriptions so replicas shard work instead of duplicating it;
- handler failures are counted + logged with trace context, never kill the
  loop.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from typing import Awaitable, Callable, Optional

from symbiont_tpu.bus.core import Msg
from symbiont_tpu.utils.telemetry import metrics, span

log = logging.getLogger(__name__)

Handler = Callable[[Msg], Awaitable[None]]


class Service:
    name = "service"

    def __init__(self, bus, max_concurrency: int = 32):
        self.bus = bus
        self._sem = asyncio.Semaphore(max_concurrency)
        self._tasks: set = set()
        self._subs: list = []
        self._loops: list = []
        self._running = False

    async def start(self) -> None:
        self._running = True
        await self._setup()

    async def _setup(self) -> None:  # override: create subscriptions
        raise NotImplementedError

    async def _subscribe_loop(self, subject: str, handler: Handler,
                              queue: Optional[str] = None,
                              durable_stream: Optional[str] = None) -> None:
        """Dispatch loop. With `durable_stream` (and a bus that supports it),
        consumption is at-least-once: the delivery is acked only after the
        handler returns, so a crash mid-handler redelivers (SURVEY.md §5.3 —
        ack-after-durable, the stance the reference's wait=true upserts take
        at the storage layer but its bus never did)."""
        durable = (durable_stream is not None and queue is not None
                   and hasattr(self.bus, "durable_subscribe"))
        if durable:
            sub = await self.bus.durable_subscribe(durable_stream, queue,
                                                   filter_subject=subject)
        else:
            sub = await self.bus.subscribe(subject, queue=queue)
        self._subs.append(sub)

        async def loop() -> None:
            async for msg in sub:
                await self._sem.acquire()
                task = asyncio.create_task(
                    self._run_handler(subject, handler, msg, ack=durable))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)

        t = asyncio.create_task(loop(), name=f"{self.name}:{subject}")
        self._loops.append(t)

    async def _run_handler(self, subject: str, handler: Handler, msg: Msg,
                           ack: bool = False) -> None:
        try:
            metrics.inc("bus.consumed",
                        labels={"service": self.name, "subject": subject})
            with span(f"{self.name}.handle", msg.headers,
                      subject=subject) as sp:
                # hand the handler a PRIVATE message bound to this handler
                # span's context: the inproc bus shares one Msg (and one
                # headers dict) across all subscribers, so rebinding a copy
                # — never mutating the original — is what lets every
                # downstream publish link to this span without racing a
                # sibling subscriber's handler (obs trace model; the ack
                # below still uses the ORIGINAL msg, whose transport
                # headers the copy merge also preserves)
                hmsg = dataclasses.replace(
                    msg, headers={**(msg.headers or {}), **sp.headers})
                await handler(hmsg)
            if ack:
                # ack-after-success: a failed handler leaves the message
                # unacked for redelivery
                await self.bus.ack(msg)
        except Exception:
            metrics.inc("bus.failed",
                        labels={"service": self.name, "subject": subject})
            log.exception("%s: handler failed for %s", self.name, subject)
        finally:
            self._sem.release()

    async def stop(self) -> None:
        self._running = False
        for s in self._subs:
            s.close()
        for t in self._loops:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._loops.clear()
        self._subs.clear()
