"""Preprocessing service — the engine's bus frontend.

Parity with reference: services/preprocessing_service/src/main.rs, two roles:
1. pipeline: data.raw_text.discovered → clean/split/embed →
   data.text.with_embeddings (main.rs:126-171), with errors for empty text
   (main.rs:33-39);
2. query embedding request-reply on tasks.embedding.for_query with typed
   error replies even on bad input (main.rs:173-298).

Plus the deliberate un-orphaning (SURVEY.md fact #3): after embedding, the
tokenized form is published to data.processed_text.tokenized so the
knowledge-graph path is live again (the reference's CHANGELOG.md:57-60 left it
dead).

Embedding runs through the MicroBatcher — queries and bulk ingest share the
engine without the reference's concurrent-forward hazard (§5.2).
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.engine.batcher import MicroBatcher
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.engine.text import clean_text, split_sentences, tokenize_words
from symbiont_tpu.schema import (
    QueryEmbeddingResult,
    QueryForEmbeddingTask,
    RawTextMessage,
    TokenizedTextMessage,
    from_json,
    to_json_bytes,
)
from symbiont_tpu.schema import frames
from symbiont_tpu.resilience import admission
from symbiont_tpu.services.base import Service
from symbiont_tpu.utils.ids import current_timestamp_ms
from symbiont_tpu.utils.telemetry import child_headers, metrics

log = logging.getLogger(__name__)


class PreprocessingService(Service):
    name = "preprocessing"

    def __init__(self, bus, engine: TpuEngine,
                 batcher: Optional[MicroBatcher] = None,
                 publish_tokenized: bool = True,
                 durable_stream: Optional[str] = None,
                 use_frames: Optional[bool] = None):
        super().__init__(bus)
        self.engine = engine
        self.batcher = batcher or MicroBatcher(engine)
        self.publish_tokenized = publish_tokenized
        self.model_name = engine.config.model_name
        self.durable_stream = durable_stream
        # binary tensor frames on data.text.with_embeddings (schema/frames);
        # None → the SYMBIONT_FRAMES deployment knob (default on)
        self.use_frames = (frames.frames_enabled() if use_frames is None
                           else use_frames)

    async def start(self) -> None:
        await self.batcher.start()
        await super().start()

    async def stop(self) -> None:
        await super().stop()
        await self.batcher.close()

    async def _setup(self) -> None:
        await self._subscribe_loop(subjects.DATA_RAW_TEXT_DISCOVERED,
                                   self._handle_raw_text,
                                   queue=subjects.QUEUE_PREPROCESSING,
                                   durable_stream=self.durable_stream)
        await self._subscribe_loop(subjects.TASKS_EMBEDDING_FOR_QUERY,
                                   self._handle_query_embedding,
                                   queue=subjects.QUEUE_PREPROCESSING)

    # ------------------------------------------------------------- pipeline

    async def _handle_raw_text(self, msg: Msg) -> None:
        raw = from_json(RawTextMessage, msg.data)
        cleaned = clean_text(raw.raw_text)
        if not cleaned:
            metrics.inc("preprocessing.empty_text")
            log.warning("cleaned text empty for id %s", raw.id)
            return
        sentences = split_sentences(cleaned)
        # engine-plane fairness: the tenant header threaded from the edge
        # picks this document's lane in the micro-batcher — fairness holds
        # even when the API edge's admission plane is bypassed or restarted
        vectors = await self.batcher.embed(
            sentences, tenant=admission.tenant_of(msg.headers))
        # engine output → wire without a single per-float Python conversion:
        # frame mode appends the [n, dim] f32 block to the JSON metadata
        # (schema/frames); fallback mode emits the reference wire shape
        data, fheaders = frames.encode_embeddings_message(
            raw.id, raw.source_url, sentences, vectors, self.model_name,
            current_timestamp_ms(), use_frame=self.use_frames)
        headers = child_headers(msg.headers)
        # the frame header rides ONLY on the frame-bearing publish — the
        # tokenized publish below shares the trace context, not the frame
        await self.bus.publish(subjects.DATA_TEXT_WITH_EMBEDDINGS,
                               data, headers={**headers, **fheaders})
        metrics.inc("preprocessing.embedded_docs")
        metrics.inc("preprocessing.embedded_sentences", len(sentences))
        if self.publish_tokenized:
            tok = TokenizedTextMessage(
                original_id=raw.id, source_url=raw.source_url,
                tokens=tokenize_words(cleaned), sentences=sentences,
                timestamp_ms=current_timestamp_ms())
            await self.bus.publish(subjects.DATA_PROCESSED_TEXT_TOKENIZED,
                                   to_json_bytes(tok), headers=headers)

    # ------------------------------------------------------ query embedding

    async def _handle_query_embedding(self, msg: Msg) -> None:
        if not msg.reply:
            log.warning("query-embedding task without reply inbox")
            return
        try:
            task = from_json(QueryForEmbeddingTask, msg.data)
        except Exception as e:
            # typed error reply even on deserialize failure (main.rs:183-196)
            err = QueryEmbeddingResult(request_id="unknown", embedding=None,
                                       model_name=None,
                                       error_message=f"bad request: {e}")
            await self.bus.publish(msg.reply, to_json_bytes(err))
            return
        try:
            # interactive lane (batcher.interactive_lane): the query must
            # stride-interleave against this tenant's own bulk-ingest lane,
            # not FIFO behind it — a deep ingest backlog otherwise turns
            # every same-tenant search into a bus-timeout (load_ramp tier)
            from symbiont_tpu.engine.batcher import interactive_lane

            vecs = await self.batcher.embed(
                [task.text_to_embed],
                tenant=interactive_lane(admission.tenant_of(msg.headers)))
            if frames.wants_frame(msg.headers):
                # negotiated reply frame (X-Symbiont-Accept-Frame): the
                # [1, dim] block rides appended to a schema-valid reply
                # whose embedding list is empty — no per-float JSON on the
                # reply hop. Requesters that never sent the header (the
                # reference-era C++ gateway included) keep getting float
                # lists below.
                arr = np.ascontiguousarray(
                    np.asarray(vecs[:1], np.float32))
                result = QueryEmbeddingResult(
                    request_id=task.request_id, embedding=[],
                    model_name=self.model_name, error_message=None)
                data, fheaders = frames.attach_frame(to_json_bytes(result),
                                                     arr)
                await self.bus.publish(
                    msg.reply, data,
                    headers={**child_headers(msg.headers), **fheaders})
                metrics.inc("preprocessing.query_embeddings")
                return
            result = QueryEmbeddingResult(
                request_id=task.request_id,
                embedding=np.asarray(vecs[0], np.float32).tolist(),
                model_name=self.model_name, error_message=None)
        except Exception as e:
            log.exception("query embedding failed")
            result = QueryEmbeddingResult(request_id=task.request_id,
                                          embedding=None, model_name=None,
                                          error_message=str(e))
        await self.bus.publish(msg.reply, to_json_bytes(result),
                               headers=child_headers(msg.headers))
        metrics.inc("preprocessing.query_embeddings")
