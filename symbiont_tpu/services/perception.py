"""Perception service — web scraper.

Parity with reference: services/perception_service/src/main.rs.
Consumes PerceiveUrlTask from tasks.perceive.url (queue-grouped here),
fetches with a 15s timeout + custom UA (main.rs:89-94), extracts main content
via the selector cascade (html_extract.py), publishes RawTextMessage to
data.raw_text.discovered (main.rs:67-69). Empty extractions are dropped with
a warning, matching scrape_and_publish (main.rs:15-84).
"""

from __future__ import annotations

import asyncio
import logging
import urllib.request
from typing import Optional

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.config import PerceptionConfig
from symbiont_tpu.schema import PerceiveUrlTask, RawTextMessage, from_json, to_json_bytes
from symbiont_tpu.services.base import Service
from symbiont_tpu.services.html_extract import extract_main_text
from symbiont_tpu.utils.ids import current_timestamp_ms, generate_uuid
from symbiont_tpu.utils.telemetry import child_headers, metrics

log = logging.getLogger(__name__)


class PerceptionService(Service):
    name = "perception"

    def __init__(self, bus, config: Optional[PerceptionConfig] = None,
                 fetcher=None):
        super().__init__(bus)
        self.config = config or PerceptionConfig()
        # fetcher injectable for tests (the seam the reference has but never
        # uses, SURVEY.md §4)
        self._fetch = fetcher or self._http_fetch

    async def _setup(self) -> None:
        await self._subscribe_loop(subjects.TASKS_PERCEIVE_URL,
                                   self._handle_task,
                                   queue=subjects.QUEUE_PERCEPTION)

    def _http_fetch(self, url: str) -> str:
        req = urllib.request.Request(
            url, headers={"User-Agent": self.config.user_agent})
        with urllib.request.urlopen(req, timeout=self.config.scrape_timeout_s) as r:
            charset = r.headers.get_content_charset() or "utf-8"
            return r.read().decode(charset, errors="replace")

    async def _handle_task(self, msg: Msg) -> None:
        task = from_json(PerceiveUrlTask, msg.data)
        try:
            html = await asyncio.get_running_loop().run_in_executor(
                None, self._fetch, task.url)
        except Exception as e:
            metrics.inc("perception.scrape_failed")
            log.warning("scrape failed for %s: %s", task.url, e)
            return
        text = extract_main_text(html)
        if not text:
            metrics.inc("perception.empty_extraction")
            log.warning("no meaningful text extracted from %s", task.url)
            return
        out = RawTextMessage(id=generate_uuid(), source_url=task.url,
                             raw_text=text, timestamp_ms=current_timestamp_ms())
        await self.bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                               to_json_bytes(out),
                               headers=child_headers(msg.headers))
        metrics.inc("perception.published")
