"""Knowledge-graph service — consumes the (restored) tokenized stream AND
serves graph-augmented search.

Parity with reference: services/knowledge_graph_service/src/main.rs:142-156
(handler) and :23-140 (save), over the embedded sqlite graph store instead of
external Neo4j. In the reference this consumer is orphaned — nothing publishes
its subject in v0.3.0 (SURVEY.md fact #3); here preprocessing publishes it,
and the limb is finally LOAD-BEARING end-to-end: the tokenized stream builds
the Document/Sentence/Token graph, and `tasks.search.graph.request` (behind
`POST /api/search/graph`) answers token-overlap document lookups over it —
entity extraction → graph upsert → graph-augmented search as one traced
scenario (bench/load.py drives it under the traffic simulator).
"""

from __future__ import annotations

import asyncio
import logging
import re

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.graph.store import GraphStore
from symbiont_tpu.schema import TokenizedTextMessage, from_json
from symbiont_tpu.services.base import Service
from symbiont_tpu.utils.telemetry import child_headers, metrics, span

log = logging.getLogger(__name__)

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


class KnowledgeGraphService(Service):
    name = "knowledge_graph"

    # documents scanned per query token before ranking; bounds the work a
    # single pathological query (every stopword in the corpus) can cause
    MAX_DOCS_PER_TOKEN = 256

    def __init__(self, bus, store: GraphStore, durable_stream=None):
        super().__init__(bus)
        self.store = store
        self.durable_stream = durable_stream

    async def _setup(self) -> None:
        # retry-at-startup parity (main.rs:253-284), in an executor: with an
        # external-Neo4j backend this is a blocking HTTP retry loop
        await asyncio.get_running_loop().run_in_executor(
            None, self.store.ensure_schema)
        await self._subscribe_loop(subjects.DATA_PROCESSED_TEXT_TOKENIZED,
                                   self._handle_tokenized,
                                   queue=subjects.QUEUE_KNOWLEDGE_GRAPH,
                                   durable_stream=self.durable_stream)
        await self._subscribe_loop(subjects.TASKS_SEARCH_GRAPH_REQUEST,
                                   self._handle_graph_search,
                                   queue=subjects.QUEUE_KNOWLEDGE_GRAPH)

    async def _handle_tokenized(self, msg: Msg) -> None:
        m = from_json(TokenizedTextMessage, msg.data)
        with span("knowledge_graph.save", msg.headers,
                  sentences=len(m.sentences), tokens=len(m.tokens)):
            await asyncio.get_running_loop().run_in_executor(
                None, self.store.save_tokenized, m)
        metrics.inc("knowledge_graph.documents_saved")

    # ------------------------------------------------ graph-augmented search

    def _graph_search(self, query_text: str, top_k: int) -> list:
        """Token-overlap document ranking over the graph: the query's
        tokens → Token nodes → CONTAINS_TOKEN edges → Documents, scored by
        matched-token count (ties by id, deterministic), each hit carrying
        its leading sentences as the snippet."""
        tokens = [t.lower() for t in _TOKEN_RE.findall(query_text)]
        seen, uniq = set(), []
        for t in tokens:
            if t not in seen:
                seen.add(t)
                uniq.append(t)
        match_counts: dict = {}
        matched_by_doc: dict = {}
        for token in uniq:
            for doc_id in self.store.documents_containing_token(
                    token, limit=self.MAX_DOCS_PER_TOKEN):
                match_counts[doc_id] = match_counts.get(doc_id, 0) + 1
                matched_by_doc.setdefault(doc_id, []).append(token)
        ranked = sorted(match_counts.items(), key=lambda kv: (-kv[1], kv[0]))
        hits = []
        for doc_id, n in ranked[:top_k]:
            sentences = self.store.document_sentences(doc_id)
            hits.append({
                "original_document_id": doc_id,
                "matched_tokens": matched_by_doc[doc_id],
                "match_count": n,
                "snippet": " ".join(sentences[:2]),
            })
        return hits

    async def _handle_graph_search(self, msg: Msg) -> None:
        """Request-reply: {"query_text": ..., "top_k": N} → {"results":
        [...], "error_message": null}. Plain JSON wire (engine-plane
        convention), NOT a schema dataclass — this subject is framework-
        internal, not part of the reference parity surface."""
        import json as _json

        if not msg.reply:
            log.warning("graph search task without reply inbox")
            return
        try:
            req = _json.loads(msg.data)
            query_text = req.get("query_text") or ""
            top_k = max(1, min(int(req.get("top_k", 5)), 100))
            if not isinstance(query_text, str) or not query_text.strip():
                raise ValueError("query_text must be a non-empty string")
            with span("knowledge_graph.search", msg.headers, top_k=top_k):
                if not hasattr(self.store, "documents_containing_token"):
                    raise RuntimeError(
                        "graph backend has no token-lookup surface "
                        "(external Neo4j adapter: use Cypher directly)")
                hits = await asyncio.get_running_loop().run_in_executor(
                    None, self._graph_search, query_text, top_k)
            body = {"results": hits, "error_message": None}
        except Exception as e:
            log.exception("graph search failed")
            body = {"results": [], "error_message": str(e)}
        await self.bus.publish(
            msg.reply, _json.dumps(body, ensure_ascii=False).encode(),
            headers=child_headers(msg.headers))
        metrics.inc("knowledge_graph.graph_searches")
