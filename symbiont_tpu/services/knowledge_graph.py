"""Knowledge-graph service — consumes the (restored) tokenized stream.

Parity with reference: services/knowledge_graph_service/src/main.rs:142-156
(handler) and :23-140 (save), over the embedded sqlite graph store instead of
external Neo4j. In the reference this consumer is orphaned — nothing publishes
its subject in v0.3.0 (SURVEY.md fact #3); here preprocessing publishes it.
"""

from __future__ import annotations

import asyncio
import logging

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.graph.store import GraphStore
from symbiont_tpu.schema import TokenizedTextMessage, from_json
from symbiont_tpu.services.base import Service
from symbiont_tpu.utils.telemetry import metrics, span

log = logging.getLogger(__name__)


class KnowledgeGraphService(Service):
    name = "knowledge_graph"

    def __init__(self, bus, store: GraphStore, durable_stream=None):
        super().__init__(bus)
        self.store = store
        self.durable_stream = durable_stream

    async def _setup(self) -> None:
        # retry-at-startup parity (main.rs:253-284), in an executor: with an
        # external-Neo4j backend this is a blocking HTTP retry loop
        await asyncio.get_running_loop().run_in_executor(
            None, self.store.ensure_schema)
        await self._subscribe_loop(subjects.DATA_PROCESSED_TEXT_TOKENIZED,
                                   self._handle_tokenized,
                                   queue=subjects.QUEUE_KNOWLEDGE_GRAPH,
                                   durable_stream=self.durable_stream)

    async def _handle_tokenized(self, msg: Msg) -> None:
        m = from_json(TokenizedTextMessage, msg.data)
        with span("knowledge_graph.save", msg.headers,
                  sentences=len(m.sentences), tokens=len(m.tokens)):
            await asyncio.get_running_loop().run_in_executor(
                None, self.store.save_tokenized, m)
        metrics.inc("knowledge_graph.documents_saved")
