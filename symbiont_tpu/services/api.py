"""API gateway — HTTP + SSE frontend, bus client behind.

Parity with reference: services/api_service/src/main.rs (§1-L4 contract —
the reference's Next.js frontend works against this unmodified):

- POST /api/submit-url      → publish tasks.perceive.url (main.rs:42-111)
- POST /api/generate-text   → validate task_id/max_length 1..=1000, publish
                              tasks.generation.text (main.rs:113-188)
- GET  /api/events          → SSE stream of events.text.generated with 15s
                              keep-alive, drop-on-lag (main.rs:190-270)
- POST /api/search/semantic → 2-hop request-reply orchestration with 15s/20s
                              timeouts and the reference's exact status-code /
                              error-body mapping (main.rs:272-512)
- CORS on localhost origins (main.rs:555-567)

Additions (SURVEY.md §5.5/§5.3 plans): GET /api/metrics (JSON snapshot),
GET /metrics (Prometheus text exposition; OpenMetrics with exemplars when
negotiated), GET /healthz, and the flight-recorder query surface:
GET /api/traces/recent, GET /api/traces/<trace_id> (span tree),
GET /api/traces/<trace_id>/critical_path (latency attribution,
obs/critical_path.py) and GET /api/traces/<trace_id>/export?fmt=chrome
(Perfetto-loadable Chrome Trace Format, obs/chrome_trace.py).

Server: stdlib asyncio HTTP/1.1 — no web framework; this is the Python twin of
the native C++ gateway under native/.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from symbiont_tpu import subjects
from symbiont_tpu.config import ApiConfig, BusConfig
from symbiont_tpu.schema import (
    GenerateTextTask,
    QueryEmbeddingResult,
    QueryForEmbeddingTask,
    SemanticSearchApiRequest,
    SemanticSearchApiResponse,
    SemanticSearchNatsResult,
    SemanticSearchNatsTask,
    from_dict,
    from_json,
    to_json,
    to_json_bytes,
)
from symbiont_tpu.schema import frames
from symbiont_tpu.resilience import admission as adm
from symbiont_tpu.resilience.admission import (
    AdmissionController,
    AdmissionReject,
    DegradationLadder,
)
from symbiont_tpu.utils.ids import generate_uuid
from symbiont_tpu.utils.telemetry import (
    DEADLINE_HEADER,
    SPAN_HEADER,
    TENANT_HEADER,
    TRACE_HEADER,
    metrics,
    new_trace_headers,
    span,
)

log = logging.getLogger(__name__)

import re

# exact host (+optional port): http://localhost.evil.com must NOT match
_ORIGIN_RE = re.compile(r"^https?://(localhost|127\.0\.0\.1)(:\d+)?$")

_LAGGED = object()  # queue sentinel: this client fell behind → terminal close


class _HttpError(Exception):
    """Malformed/oversized request — answered with a status, then close.

    Carries the request Origin (headers are parsed before the body checks
    fire) so the error response gets CORS headers — a browser client must be
    able to read the 413/400, same as the C++ twin."""

    def __init__(self, status: int, message: str, origin: Optional[str] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.origin = origin


def _deadline_capped(default_s: float, headers: Dict[str, str]) -> float:
    """A bus-request timeout never longer than the request's remaining
    deadline budget: downstream services drop expired deliveries WITHOUT
    replying, so waiting out the full transport timeout would pin a fair-
    queue slot for dead work — up to 2x the deadline — exactly when
    shedding should be freeing capacity. Floor keeps a just-expiring
    request failing fast instead of with timeout=0 weirdness."""
    rem = adm.remaining_ms(headers)
    if rem is None:
        return default_s
    return max(0.05, min(default_s, rem / 1000.0))


@contextlib.asynccontextmanager
async def _fair_slot(admission, tenant: str):
    """Hold one weighted-fair search-concurrency slot for the block (no-op
    without an admission controller); released on every exit path."""
    if admission is not None:
        await admission.fair_queue.acquire(tenant)
    try:
        yield
    finally:
        if admission is not None:
            admission.fair_queue.release(tenant)


class _SseClient:
    __slots__ = ("q", "want", "lagged")

    def __init__(self, capacity: int, want: Optional[str]):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=capacity)
        self.want = want      # task_id filter (None = receive everything)
        self.lagged = False   # fell behind; terminal close pending


class _SseHub:
    """Bounded broadcast: capacity-32 queues (reference: broadcast channel
    cap 32, main.rs:537) — but a lagged client gets an explicit terminal
    `retry:` + error close instead of the reference's silent message drop
    (main.rs:201-209), so a slow reader KNOWS its stream has a gap and can
    reconnect with Last-Event-ID instead of serving truncated text.

    Clients may register with a task_id filter (?task_id= on /api/events):
    the reference broadcasts every generation event to every SSE client
    (main.rs:215-270 — its UI correlates by original_task_id client-side);
    unfiltered clients keep that behavior, filtered ones receive only their
    task's events.

    Exactly-once edge (docs/RESILIENCE.md "Durable generation sessions"):
    an adopted resume replays its last journaled chunk under the chunk's
    ORIGINAL seq, so a crash between journal-append and delivery loses
    nothing — and the hub drops anything at-or-below the highest seq already
    delivered for that task, so the overlap case duplicates nothing either.
    Delivered chunks are stamped `id: <task_id>:<seq>` on the wire and kept
    in a bounded per-task history; a reconnecting client's Last-Event-ID
    replays the tail it missed. Both maps are bounded, oldest task out."""

    def __init__(self, capacity: int = 32, history_tasks: int = 256,
                 history_events: int = 128):
        self.capacity = capacity
        self._clients: List[_SseClient] = []
        self._last_seq: "OrderedDict[str, int]" = OrderedDict()
        # task_id → deque[(seq, payload, done)] of delivered chunks
        self._history: "OrderedDict[str, deque]" = OrderedDict()
        self._history_tasks = history_tasks
        self._history_events = history_events

    def register(self, task_id: Optional[str] = None,
                 last_event_id: Optional[str] = None) -> _SseClient:
        c = _SseClient(self.capacity, task_id)
        if last_event_id:
            # Last-Event-ID: "<task_id>:<seq>" → replay the missed tail
            # from history before any live event (queue is empty here, so
            # ordering holds; replay is capped at queue capacity — a gap
            # larger than that closes-with-retry like any other lag).
            tid, _, seq_s = last_event_id.rpartition(":")
            try:
                after = int(seq_s)
            except ValueError:
                tid = None
            if tid and (task_id is None or tid == task_id):
                tail = [e for e in self._history.get(tid, ())
                        if e[0] > after][-self.capacity:]
                for seq, payload, done in tail:
                    c.q.put_nowait((payload, f"{tid}:{seq}", done))
                if tail:
                    metrics.inc("api.sse_replayed", len(tail))
        self._clients.append(c)
        return c

    def unregister(self, client: _SseClient) -> None:
        self._clients = [c for c in self._clients if c is not client]

    def has_follower(self, task_id: str) -> bool:
        """Any remaining client that would receive this task's events — a
        client filtered on it, or an unfiltered (receive-everything)
        reference-style client. Consulted before cancelling a generation
        on disconnect: one of several readers leaving must not kill the
        stream for the rest."""
        return any(c.want is None or c.want == task_id
                   for c in self._clients)

    def broadcast(self, payload: str) -> None:
        tid = seq = None
        done = False
        try:
            obj = json.loads(payload)
            tid = obj.get("original_task_id")
            seq = obj.get("seq")
            done = obj.get("done") is True or "generated_text" in obj
        except (ValueError, AttributeError):
            obj = None
        sse_id = None
        if tid is not None and seq is not None:
            seq = int(seq)
            last = self._last_seq.get(tid)
            if last is not None and seq <= last:
                # resume replay of an already-delivered chunk (or the
                # requeue race after a pressure-refused adoption): the
                # exactly-once guarantee lives HERE
                metrics.inc("api.sse_deduped")
                return
            self._last_seq[tid] = seq
            self._last_seq.move_to_end(tid)
            while len(self._last_seq) > self._history_tasks:
                self._last_seq.popitem(last=False)
            hist = self._history.get(tid)
            if hist is None:
                hist = self._history[tid] = deque(
                    maxlen=self._history_events)
            self._history.move_to_end(tid)
            while len(self._history) > self._history_tasks:
                self._history.popitem(last=False)
            hist.append((seq, payload, done))
            sse_id = f"{tid}:{seq}"
        item = (payload, sse_id, done)
        for c in list(self._clients):
            if c.want is not None and tid != c.want:
                continue  # not this client's task
            if c.lagged:
                continue  # terminal close already pending
            try:
                c.q.put_nowait(item)
            except asyncio.QueueFull:
                metrics.inc("api.sse_dropped")
                log.warning("SSE client lagged; closing with retry hint")
                c.lagged = True
                # make room, then wake the handler with the lag verdict
                # (same pop-one-put trick as close_all)
                try:
                    c.q.get_nowait()
                except asyncio.QueueEmpty:
                    pass
                try:
                    c.q.put_nowait(_LAGGED)
                except asyncio.QueueFull:
                    pass

    def close_all(self) -> None:
        """Wake every SSE handler with a close sentinel (None) so graceful
        shutdown doesn't deadlock in Server.wait_closed() behind permanently
        connected clients."""
        for c in list(self._clients):
            try:
                c.q.put_nowait(None)
            except asyncio.QueueFull:
                try:
                    c.q.get_nowait()
                except asyncio.QueueEmpty:
                    pass
                try:
                    c.q.put_nowait(None)
                except asyncio.QueueFull:
                    pass


class ApiService:
    name = "api"

    def __init__(self, bus, config: Optional[ApiConfig] = None,
                 bus_config: Optional[BusConfig] = None,
                 admission: Optional[AdmissionController] = None,
                 ladder: Optional[DegradationLadder] = None,
                 gen_capacity=None, admission_config=None,
                 defer_ready: bool = False):
        self.bus = bus
        self.config = config or ApiConfig()
        self.bus_config = bus_config or BusConfig()
        self.hub = _SseHub(self.config.sse_channel_capacity)
        # overload-protection plane (resilience/admission.py, wired by the
        # runner): per-tenant quotas + weighted-fair search scheduling
        # (None = no admission control, the pre-plane behavior standalone
        # test gateways keep), the SLO shed ladder, and the LM-capacity
        # probe consulted before accepting a generation stream
        self.admission = admission
        self.ladder = ladder
        self.gen_capacity = gen_capacity  # () -> bool; None = unbounded
        self.admission_config = admission_config  # deadline budgets
        # readiness (GET /readyz): False until the hosting process says its
        # engines are placed — load balancers must not route to a cold
        # process. Standalone gateways flip ready at start() (there is
        # nothing to warm); the runner defers and calls mark_ready() once
        # the whole stack is up.
        self._ready = False
        self._defer_ready = defer_ready
        # generation task ids THIS gateway accepted (bounded, oldest out):
        # an SSE disconnect only cancels tasks known to exist — a reader
        # that pre-connected with a client-minted id and dropped before
        # ever POSTing must not tombstone the id downstream
        self._gen_submitted: dict = {}
        # fleet telemetry plane (obs/fleet.py): the runner attaches a
        # FleetAggregator in supervised deployments — /metrics then serves
        # the role-labeled federated exposition and GET /api/fleet the
        # per-role roll-up; None keeps the pre-fleet single-process surface
        # byte-identical
        self.fleet = None
        # negative cache for the fused-search subject: after a timeout
        # (subject unserved — engine and store not co-located), skip the
        # fused attempt for a window instead of stalling every request
        self._fused_down_until = 0.0
        self._server: Optional[asyncio.AbstractServer] = None
        self._bridge_tasks: List[asyncio.Task] = []
        self._bridge_subs: List = []

    def mark_ready(self) -> None:
        self._ready = True

    def mark_not_ready(self) -> None:
        """Drain protocol: a gateway being retired flips /readyz back to
        503 (and re-engages the data-path 503 gate) so the load balancer
        routes around it before the process exits."""
        self._ready = False

    # ---------------------------------------------------------------- server

    async def start(self) -> None:
        # NATS→SSE bridge (reference: nats_to_sse_listener, main.rs:215-270);
        # streaming deltas ride the same SSE channel (clients tell the two
        # payload shapes apart by their fields)
        self._bridge_subs = [
            await self.bus.subscribe(subjects.EVENTS_TEXT_GENERATED),
            await self.bus.subscribe(subjects.EVENTS_TEXT_GENERATED_PARTIAL),
        ]

        async def bridge(sub) -> None:
            async for msg in sub:
                self.hub.broadcast(msg.data.decode("utf-8", errors="replace"))

        self._bridge_tasks = [
            asyncio.create_task(bridge(s), name="sse-bridge")
            for s in self._bridge_subs]
        self._server = await asyncio.start_server(
            self._handle_conn, self.config.host, self.config.port)
        if not self._defer_ready:
            self._ready = True
        log.info("api listening on %s:%s", self.config.host, self.config.port)

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        self.hub.close_all()  # unblock SSE handlers before wait_closed
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for sub in self._bridge_subs:
            sub.close()
        for task in self._bridge_tasks:
            task.cancel()

    # ------------------------------------------------------------- plumbing

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    request = await self._read_request(reader)
                except _HttpError as e:
                    # a well-behaved client gets a status, not a dropped
                    # socket (reference error-shape conventions)
                    await self._write_response(
                        writer, e.status,
                        json.dumps({"status": "error", "message": e.message}),
                        origin=e.origin, keep_alive=False)
                    # discard any in-flight body (bounded) before closing:
                    # an immediate close with unread bytes pending RSTs the
                    # socket and can destroy the queued response client-side
                    try:
                        deadline = asyncio.get_running_loop().time() + 1.0
                        for _ in range(64):
                            left = deadline - asyncio.get_running_loop().time()
                            if left <= 0:
                                break
                            chunk = await asyncio.wait_for(
                                reader.read(65536), left)
                            if not chunk:
                                break
                    except (asyncio.TimeoutError, OSError):
                        pass
                    break
                if request is None:
                    break
                method, path, query, headers, body = request
                keep_alive = headers.get("connection", "").lower() != "close"
                if path == "/api/events" and method == "GET":
                    await self._serve_sse(writer, headers, query)
                    return  # SSE occupies the connection
                if path == "/metrics" and method == "GET":
                    # Prometheus text exposition (scrapers want text/plain,
                    # not the /api/metrics JSON snapshot). A scraper that
                    # negotiates OpenMetrics gets that flavor — same
                    # families plus exemplars on histogram buckets. With a
                    # FleetAggregator attached (obs/fleet.py, wired by the
                    # runner in supervised deployments) the exposition is
                    # FEDERATED: every role's series in one scrape, each
                    # labeled with the role that produced it.
                    from symbiont_tpu.obs import prometheus

                    om = ("application/openmetrics-text"
                          in headers.get("accept", ""))
                    if self.fleet is not None:
                        body = self.fleet.render_exposition(openmetrics=om)
                    else:
                        body = prometheus.render(openmetrics=om)
                    await self._write_response(
                        writer, 200, body,
                        origin=headers.get("origin"),
                        content_type=(prometheus.CONTENT_TYPE_OPENMETRICS
                                      if om else
                                      prometheus.CONTENT_TYPE_PROM),
                        keep_alive=keep_alive)
                    if not keep_alive:
                        break
                    continue
                if path in ("/", "/index.html") and method == "GET":
                    html = _frontend_html()
                    if html is not None:
                        await self._write_response(
                            writer, 200, html, origin=headers.get("origin"),
                            content_type="text/html; charset=utf-8",
                            keep_alive=keep_alive)
                        if not keep_alive:
                            break
                        continue
                routed = await self._route(method, path, query,
                                           headers, body)
                status, payload = routed[0], routed[1]
                # optional third element: extra response headers (e.g.
                # Retry-After on a 429 from the admission plane)
                extra = routed[2] if len(routed) > 2 else None
                await self._write_response(writer, status, payload,
                                           origin=headers.get("origin"),
                                           keep_alive=keep_alive,
                                           extra_headers=extra)
                if not keep_alive:
                    break
        except (ConnectionResetError, asyncio.IncompleteReadError, BrokenPipeError):
            pass
        except Exception:
            log.exception("connection handler error")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(self, reader) -> Optional[Tuple[str, str, Dict[str, str], bytes]]:
        try:
            line = await reader.readline()
        except (ConnectionResetError, asyncio.LimitOverrunError):
            return None
        if not line:
            return None
        try:
            method, path, _version = line.decode("latin-1").strip().split(" ", 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if b":" in h:
                k, _, v = h.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
        body = b""
        origin = headers.get("origin")
        try:
            n = int(headers.get("content-length", 0) or 0)
        except ValueError:
            raise _HttpError(400, "invalid Content-Length", origin)
        # C++ twin parity (api_gateway.cpp): cap the client-supplied length —
        # negative wraps and huge values would OOM the process
        if n < 0:
            raise _HttpError(400, "invalid Content-Length", origin)
        if n > 16 * 1024 * 1024:
            raise _HttpError(413, "request body exceeds 16MB limit", origin)
        if n:
            body = await reader.readexactly(n)
        path, _, query = path.partition("?")
        return method, path, query, headers, body

    def _cors(self, origin: Optional[str]) -> str:
        # reference allows localhost/127.0.0.1 origins (main.rs:555-567)
        if origin and _ORIGIN_RE.match(origin):
            return (f"Access-Control-Allow-Origin: {origin}\r\n"
                    "Access-Control-Allow-Methods: GET, POST, OPTIONS\r\n"
                    "Access-Control-Allow-Headers: Content-Type\r\n"
                    "Vary: Origin\r\n")
        return ""

    async def _write_response(self, writer, status: int, payload: str,
                              origin: Optional[str] = None,
                              content_type: str = "application/json",
                              keep_alive: bool = True,
                              extra_headers: Optional[Dict[str, str]] = None
                              ) -> None:
        reasons = {200: "OK", 400: "Bad Request", 404: "Not Found",
                   405: "Method Not Allowed", 413: "Payload Too Large",
                   429: "Too Many Requests",
                   500: "Internal Server Error", 503: "Service Unavailable"}
        body = payload.encode("utf-8")
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in (extra_headers or {}).items())
        head = (f"HTTP/1.1 {status} {reasons.get(status, 'OK')}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"{self._cors(origin)}"
                f"{extra}"
                f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n\r\n")
        writer.write(head.encode("latin-1") + body)
        await writer.drain()

    # --------------------------------------------------------------- routes

    async def _route(self, method: str, path: str, query: str,
                     headers: Dict[str, str],
                     body: bytes) -> Tuple[int, str]:
        if method == "OPTIONS":
            return 200, ""
        if (not self._ready and method == "POST"
                and path in ("/api/submit-url", "/api/generate-text",
                             "/api/search/semantic", "/api/search/graph")):
            # the port opens BEFORE the stack's services subscribe (so
            # /healthz and /readyz answer during engine warm-up): accepting
            # data-path work now would 200 into a bus with no consumers —
            # silent loss. Refuse honestly; a well-behaved LB watches
            # /readyz and never sends this.
            metrics.inc("api.not_ready_rejects")
            return 503, json.dumps(
                {"message": "stack is warming up (see /readyz)",
                 "task_id": None}), {"Retry-After": "1"}
        try:
            if path == "/api/submit-url" and method == "POST":
                metrics.inc("api.POST./api/submit-url")
                return await self._submit_url(body, headers)
            if path == "/api/generate-text" and method == "POST":
                metrics.inc("api.POST./api/generate-text")
                return await self._generate_text(body, headers)
            if path == "/api/search/semantic" and method == "POST":
                metrics.inc("api.POST./api/search/semantic")
                return await self._semantic_search(body, headers)
            if path == "/api/search/graph" and method == "POST":
                metrics.inc("api.POST./api/search/graph")
                return await self._graph_search(body, headers)
            if path == "/api/metrics" and method == "GET":
                return 200, json.dumps(metrics.snapshot())
            if path == "/api/traces/recent" and method == "GET":
                from symbiont_tpu.obs.trace_store import trace_store

                return 200, json.dumps({"traces": trace_store.recent()})
            if path.startswith("/api/traces/") and method == "GET":
                return self._trace_route(path[len("/api/traces/"):], query)
            if path == "/api/engine/timeline" and method == "GET":
                # decode-plane flight recorder (obs/engine_timeline.py):
                # JSON summary by default; ?fmt=chrome renders Perfetto
                # counter tracks interleaved with the flight recorder's
                # engine span lanes on one time axis
                return self._engine_timeline(query)
            if path == "/api/engine/executables" and method == "GET":
                # compute-plane profiler (obs/xprof.py): per-executable
                # dispatch counts + host wall, placed on the roofline from
                # the XLA cost model captured at compile time
                return self._engine_executables()
            if path == "/api/memory" and method == "GET":
                # hbm attribution plane (obs/hbm.py): subsystem byte
                # ledger reconciled against per-device memory_stats(),
                # fleet-federated per role when the aggregator is attached
                return self._memory()
            if path == "/api/memory/census" and method == "GET":
                # on-demand live-array census (?top=N, ?diff=1 for the
                # delta vs the previous diff baseline)
                return self._memory_census(query)
            if path == "/api/profile/device" and method == "POST":
                metrics.inc("api.POST./api/profile/device")
                return await self._profile_device(body)
            if path == "/api/tenants" and method == "GET":
                # per-tenant usage roll-up (obs/usage.py): this process's
                # ledger, plus every federated role's tenant.usage.*
                # counters when the fleet aggregator is attached
                return self._tenants_rollup()
            if path == "/api/fleet" and method == "GET":
                # per-role deployment roll-up (obs/fleet.py): telemetry
                # freshness, supervisor liveness verdicts (up / restarts /
                # hangs / heartbeat age — broker probe included), and key
                # engine gauges, one entry per role
                from symbiont_tpu.obs.hbm import oom_forensics

                if self.fleet is None:
                    return 200, json.dumps(
                        {"available": False, "roles": {},
                         "last_oom": oom_forensics.last,
                         "message": ("no fleet aggregator on this process "
                                     "— single-process stack, or "
                                     "obs.fleet_export off")})
                # the local OOM verdict rides the roll-up (remote roles'
                # counts federate as counter.engine.oom_total series)
                return 200, json.dumps(
                    {"available": True, "last_oom": oom_forensics.last,
                     **self.fleet.rollup()})
            if path == "/api/dlq" and method == "GET":
                return self._dlq_list()
            if path == "/api/dlq/replay" and method == "POST":
                metrics.inc("api.POST./api/dlq/replay")
                return await self._dlq_replay(body)
            if path == "/healthz" and method == "GET":
                # liveness ONLY: the process is up and serving HTTP. Routing
                # decisions belong to /readyz — a restart loop detector must
                # not flap with engine warm-up.
                return 200, json.dumps({"status": "ok"})
            if path == "/readyz" and method == "GET":
                # readiness: 503 until the hosting process says its engine
                # params are placed and the mesh (when parallel.enabled) is
                # built — load balancers must not route to a cold process
                if self._ready:
                    return 200, json.dumps({"status": "ready"})
                return 503, json.dumps(
                    {"status": "starting",
                     "message": "engine placement / mesh build in progress"})
            if path == "/api/health/engine" and method == "GET":
                return await self._engine_health()
            # one bucket for everything unmatched: arbitrary scanner paths
            # must not create unbounded counter cardinality
            metrics.inc("api.unmatched")
            return 404, json.dumps({"message": "not found", "task_id": None})
        except AdmissionReject as e:
            # overload answer: bounded refusal with a retry hint, never an
            # unbounded queue (resilience/admission.py; the Retry-After
            # header is what well-behaved clients back off on)
            return (429,
                    json.dumps({"message": str(e), "reason": e.reason,
                                "task_id": None}),
                    adm.retry_after_header(e.retry_after_s))
        except json.JSONDecodeError as e:
            return 400, json.dumps({"message": f"invalid JSON: {e}", "task_id": None})
        except ValueError as e:
            return 400, json.dumps({"message": str(e), "task_id": None})
        except Exception:
            log.exception("route %s failed", path)
            return 500, json.dumps({"message": "internal error", "task_id": None})

    def _trace_route(self, rest: str, query: str) -> Tuple[int, str]:
        """The flight-recorder query surface under /api/traces/<trace_id>:

        - ``…/<id>``                → parent-linked span tree
        - ``…/<id>/critical_path`` → blocking chain + self-time attribution
                                      + dominant-hop verdict
        - ``…/<id>/export?fmt=chrome`` → Chrome Trace Format JSON (load in
                                      Perfetto / chrome://tracing)
        """
        from urllib.parse import parse_qs

        from symbiont_tpu.obs.trace_store import trace_store

        trace_id, _, sub = rest.partition("/")
        not_found = (404, json.dumps(
            {"message": "trace not found (evicted from the flight "
                        "recorder, or never recorded)", "task_id": None}))
        if sub == "":
            tree = trace_store.trace_tree(trace_id)
            return not_found if tree is None else (200, json.dumps(tree))
        if sub == "critical_path":
            from symbiont_tpu.obs import critical_path

            report = critical_path.compute(trace_store, trace_id)
            return not_found if report is None else (200, json.dumps(report))
        if sub == "export":
            fmt = (parse_qs(query).get("fmt") or ["chrome"])[0]
            if fmt != "chrome":
                return 400, json.dumps(
                    {"message": f"unknown export format {fmt!r} "
                                "(supported: chrome)", "task_id": None})
            from symbiont_tpu.obs import chrome_trace

            spans = trace_store.spans_for(trace_id)
            if not spans:
                return not_found
            return 200, json.dumps(chrome_trace.export_spans(trace_id,
                                                             spans))
        return 404, json.dumps({"message": "not found", "task_id": None})

    # engine-shaped span lanes the timeline export interleaves with its
    # counter tracks (first dot-segment of the span name)
    _TIMELINE_SERVICES = ("engine", "lm", "text_generator")

    def _engine_timeline(self, query: str) -> Tuple[int, str]:
        """``GET /api/engine/timeline``: the decode-plane flight recorder's
        summary (occupancy, stranded KV, prefix share, TTFT/TPOT, dominant
        stall) or, with ``?fmt=chrome``, a Perfetto-loadable document whose
        counter tracks ride the same time axis as the engine span lanes."""
        from urllib.parse import parse_qs

        from symbiont_tpu.obs import chrome_trace
        from symbiont_tpu.obs.engine_timeline import engine_timeline
        from symbiont_tpu.obs.trace_store import trace_store

        fmt = (parse_qs(query).get("fmt") or ["json"])[0]
        events = engine_timeline.events()
        if fmt == "json":
            return 200, json.dumps({
                "summary": engine_timeline.summary(),
                "events": events[-256:],
            })
        if fmt != "chrome":
            return 400, json.dumps(
                {"message": f"unknown timeline format {fmt!r} "
                            "(supported: json, chrome)", "task_id": None})
        if not events:
            return 404, json.dumps(
                {"message": "no engine timeline recorded yet — drive some "
                            "embed/decode traffic first", "task_id": None})
        t0 = min(e["t"] for e in events) - 1.0
        t1 = max(e["t"] for e in events) + 1.0
        spans = []
        for trace_spans in trace_store.spans_by_trace().values():
            for r in trace_spans:
                if (chrome_trace.service_of(r.name) in self._TIMELINE_SERVICES
                        and t0 <= r.start_s <= t1):
                    spans.append(r)
        doc = chrome_trace.export_timeline("engine-timeline", spans, events)
        # cross-link the newest on-demand device trace (obs/xprof.py): a
        # reader correlating the host-side timeline with real device
        # kernels finds the XProf artifact without leaving the export.
        # Mutated HERE, not in chrome_trace — the span/timeline goldens
        # pin chrome_trace's own output byte-for-byte.
        from symbiont_tpu.obs.xprof import device_trace

        if device_trace.last_artifact:
            doc.setdefault("otherData", {})["device_trace_artifact"] = \
                device_trace.last_artifact
        return 200, json.dumps(doc)

    def _engine_executables(self) -> Tuple[int, str]:
        """``GET /api/engine/executables``: the dispatch ledger's
        per-executable rows (counts, host wall, compiles, XLA cost model)
        graded through the roofline accountant. Achieved rates divide
        cost-model work by MEASURED host wall per dispatch — the gap
        between these and a device-trace number is host overhead, which
        is exactly what the compute-plane profiler exists to expose."""
        from symbiont_tpu.bench.roofline import grade_executable
        from symbiont_tpu.obs.xprof import device_trace, dispatch_ledger

        rows = dispatch_ledger.snapshot()
        for r in rows:
            r.update(grade_executable(
                r["flops"], r["bytes_accessed"],
                r["host_wall_ms"] / 1000.0, r["dispatches"]))
        return 200, json.dumps({
            "executables": rows,
            "total_dispatches": sum(r["dispatches"] for r in rows),
            "device_trace_artifact": device_trace.last_artifact,
        })

    def _memory(self) -> Tuple[int, str]:
        """``GET /api/memory``: the hbm ledger reconciled against device
        reality — per-subsystem claims, per-device bytes in use / limit,
        the unattributed residual, and the last OOM verdict. With the
        fleet aggregator attached, every remote role's ``hbm.*`` /
        ``device.bytes*`` gauges fold in per role, so the autoscaler reads
        REAL fleet-wide headroom from one endpoint."""
        import time as _time

        from symbiont_tpu.obs.hbm import hbm_ledger, oom_forensics
        from symbiont_tpu.obs.prometheus import parse_flat_key

        roles: Dict[str, dict] = {}
        if self.fleet is not None:
            for role, flat in self.fleet.role_snapshots().items():
                for key, v in flat.items():
                    parsed = parse_flat_key(key)
                    if parsed is None:
                        continue
                    kind, name, labels, stat = parsed
                    if (kind != "gauge" or stat is not None
                            or not (name.startswith("hbm.")
                                    or name.startswith("device.bytes")
                                    or name == "lm.hbm_headroom_bytes")):
                        continue
                    entry = roles.setdefault(role, {})
                    if name == "hbm.attributed_bytes":
                        sub = labels.get("subsystem") or "all"
                        entry.setdefault("subsystems", {})[sub] = v
                    else:
                        lbl = ",".join(f"{k}={labels[k]}"
                                       for k in sorted(labels))
                        entry.setdefault("series", {})[
                            f"{name}{{{lbl}}}" if lbl else name] = v
        return 200, json.dumps({
            "generated_at": round(_time.time(), 3),
            "local": hbm_ledger.reconcile(),
            "last_oom": oom_forensics.last,
            "roles": roles,
        })

    def _memory_census(self, query: str) -> Tuple[int, str]:
        """``GET /api/memory/census``: aggregate ``jax.live_arrays()`` by
        (shape, dtype, sharding) — host metadata only, on demand only.
        ``?top=N`` bounds group rows (default obs.hbm_census_groups);
        ``?diff=1`` returns the delta against the previous diff call's
        snapshot (and re-arms the baseline), turning "HBM grew" into the
        owning allocation group."""
        from urllib.parse import parse_qs

        from symbiont_tpu.obs import hbm

        q = parse_qs(query)
        try:
            top = int((q.get("top") or [hbm.hbm_ledger.census_groups])[0])
        except ValueError:
            raise ValueError("top must be an integer")
        if (q.get("diff") or ["0"])[0] not in ("0", "", "false"):
            # diff snapshots are UNBOUNDED (top=0): a leaked group must
            # not hide inside the bounded census's "(other)" fold. Only
            # the returned delta rows are bounded.
            now = hbm.census(top=0)
            before, self._census_baseline = (
                getattr(self, "_census_baseline", None), now)
            summary = {k: now.get(k) for k in
                       ("available", "arrays", "bytes_total")}
            if before is None:
                return 200, json.dumps({
                    "baseline_armed": True, "census": summary,
                    "message": ("no prior baseline — this census is now "
                                "the baseline; call ?diff=1 again to see "
                                "the delta")})
            return 200, json.dumps(
                {"diff": hbm.census_diff(before, now, top=max(1, top)),
                 "census": summary})
        return 200, json.dumps({"census": hbm.census(top=max(1, top))})

    async def _profile_device(self, body: bytes) -> Tuple[int, str]:
        """``POST /api/profile/device``: capture a bounded on-demand
        jax.profiler device trace window ({"duration_s": 1.0}, clamped to
        obs.xprof_trace_max_s) and return the artifact path. Runs on an
        executor thread — the capture SLEEPS through its window and must
        not stall the event loop; concurrency is resolved by the process-
        global profiler lock (409 when a capture is already in flight)."""
        from symbiont_tpu.obs.xprof import device_trace

        payload = json.loads(body.decode("utf-8")) if body.strip() else {}
        if not isinstance(payload, dict):
            raise ValueError("body must be a JSON object")
        duration = payload.get("duration_s", 1.0)
        loop = asyncio.get_running_loop()
        res = await loop.run_in_executor(None, device_trace.capture,
                                         duration)
        status = {"captured": 200, "busy": 409, "error": 500}[res["status"]]
        return status, json.dumps(res)

    def _tenants_rollup(self) -> Tuple[int, str]:
        """``GET /api/tenants``: local per-tenant usage totals, plus the
        federated per-role view folded from each role's
        ``tenant.usage.*`` counters (obs/fleet.py snapshots) when this
        process hosts the fleet aggregator."""
        import time as _time

        from symbiont_tpu.obs.prometheus import parse_flat_key
        from symbiont_tpu.obs.usage import usage

        roles: Dict[str, dict] = {}
        if self.fleet is not None:
            for role, flat in self.fleet.role_snapshots().items():
                for key, v in flat.items():
                    parsed = parse_flat_key(key)
                    if parsed is None:
                        continue
                    kind, name, labels, stat = parsed
                    if (kind != "counter" or stat is not None
                            or not name.startswith("tenant.usage.")):
                        continue
                    tenant = labels.get("tenant") or "default"
                    roles.setdefault(role, {}).setdefault(tenant, {})[
                        name[len("tenant.usage."):]] = v
        return 200, json.dumps({
            "generated_at": round(_time.time(), 3),
            "tenants": usage.snapshot(),
            "roles": roles,
        })

    # ------------------------------------------------------- admission edge

    @staticmethod
    def _trace_ctx(headers: Optional[Dict[str, str]]):
        """Inbound HTTP trace context → span parent. A client carrying
        X-Trace-Id/X-Span-Id across calls (the RAG flow in bench/load.py:
        search → rerank → generate) gets ONE flight-recorder trace instead
        of three; absent headers keep the old mint-per-request behavior."""
        if headers and "x-trace-id" in headers:
            ctx = {TRACE_HEADER: headers["x-trace-id"]}
            if "x-span-id" in headers:
                ctx[SPAN_HEADER] = headers["x-span-id"]
            return ctx
        return None

    def _degraded_top_k(self, tenant: str, top_k: int) -> Tuple[int, bool]:
        """Ladder rung-2 clamp shared by BOTH search surfaces (semantic +
        graph): returns (possibly-clamped top_k, degraded?) and counts the
        degraded serve — degrade, don't fail, while the SLO recovers."""
        if self.ladder is None or not self.ladder.search_degraded():
            return top_k, False
        metrics.inc("admission.degraded",
                    labels={"what": "search", "tenant": tenant})
        return self.ladder.degrade_top_k(top_k), True

    def _search_slot(self, tenant: str):
        """One weighted-fair concurrency slot over the shared search
        budget (both search surfaces ride it — a storm on either cannot
        sidestep the bounded fair queue). Async context manager; a no-op
        without an admission controller."""
        return _fair_slot(self.admission, tenant)

    def _edge_admit(self, klass: str, headers: Dict[str, str],
                    priority: str = "normal") -> Tuple[str, Dict[str, str]]:
        """The one admission gate every ingress class passes: already-
        expired client deadline → reject (no bus publish); shed ladder
        (generation only; ingest is NEVER shed); per-tenant quota; LM
        capacity (generation only). Returns (tenant, headers-to-thread):
        tenant identity plus the deadline minted for this class's budget.
        Raises AdmissionReject — answered 429 + Retry-After by _route."""
        tenant = adm.tenant_of(headers)
        if self.admission is not None:
            # client-supplied header → bounded identity universe (past the
            # cap, new tenants share the overflow bucket/queue)
            tenant = self.admission.resolve_tenant(tenant)
        if adm.expired(headers):
            # the caller's own deadline has passed: doing the work (or even
            # publishing it) serves nobody
            metrics.inc("admission.expired",
                        labels={"service": self.name, "subject": "edge"})
            raise AdmissionReject(
                "deadline", retry_after_s=1.0,
                message="request deadline already expired at the edge")
        if klass == "generate":
            if self.ladder is not None:
                reason = self.ladder.shed_generation(priority)
                if reason is not None:
                    metrics.inc("admission.shed",
                                labels={"reason": reason, "tenant": tenant})
                    raise AdmissionReject(
                        reason, retry_after_s=self._shed_retry_after_s(),
                        message=f"generation shed under SLO pressure "
                                f"({reason}, priority {priority})")
            if self.gen_capacity is not None and not self.gen_capacity():
                metrics.inc("admission.shed",
                            labels={"reason": "kv_capacity",
                                    "tenant": tenant})
                raise AdmissionReject(
                    "kv_capacity", retry_after_s=2.0,
                    message="generation capacity exhausted (KV rows at "
                            "the admission bound)")
        if self.admission is not None:
            self.admission.admit(klass, tenant)  # raises on quota
        extra = {TENANT_HEADER: tenant}
        budget = 0.0
        if self.admission_config is not None:
            budget = getattr(self.admission_config, f"deadline_{klass}_ms")
        deadline = adm.mint_deadline(budget, headers)
        if deadline is not None:
            extra[DEADLINE_HEADER] = deadline
        return tenant, extra

    @staticmethod
    def _meter_search(tenant: str) -> None:
        """Usage ledger (obs/usage.py): one ADMITTED search query billed to
        its tenant — 429s never bill (refused work is not usage)."""
        from symbiont_tpu.obs.usage import usage

        usage.note(tenant, search_queries=1)

    def _shed_retry_after_s(self) -> float:
        """Sheds hint a longer back-off than quota refills: the ladder only
        steps down after recovery passes × the watchdog interval."""
        return 5.0

    async def _submit_url(self, body: bytes,
                          headers: Dict[str, str]) -> Tuple[int, str]:
        data = json.loads(body)
        url = (data.get("url") or "").strip()
        if not url:
            # reference: main.rs:48-53
            return 400, json.dumps({"message": "URL cannot be empty", "task_id": None})
        _tenant, extra = self._edge_admit("ingest", headers)
        # root span of the ingest pipeline trace: every downstream handler
        # span (perception → preprocessing → vector_memory/knowledge_graph)
        # links back to this one in the flight recorder; the deadline +
        # tenant headers thread through every hop via child_headers
        with span("api.submit_url", self._trace_ctx(headers), url=url) as sp:
            await self.bus.publish(subjects.TASKS_PERCEIVE_URL,
                                   to_json_bytes_url(url),
                                   headers={**sp.headers, **extra})
        return 200, json.dumps({
            "message": f"Task to scrape URL '{url}' submitted successfully.",
            "task_id": None})

    async def _generate_text(self, body: bytes,
                             headers: Dict[str, str]) -> Tuple[int, str]:
        task = from_dict(GenerateTextTask, json.loads(body))
        if not task.task_id.strip():
            # reference: main.rs:125-131
            return 400, json.dumps({"message": "task_id cannot be empty",
                                    "task_id": None})
        if task.max_length == 0 or task.max_length > self.config.max_gen_length:
            # reference: main.rs:133-142 (bound configurable here)
            return 400, json.dumps({
                "message": f"max_length must be between 1 and {self.config.max_gen_length}",
                "task_id": task.task_id})
        # sampling overrides (our addition): bound them here so a bad value
        # fails fast at the HTTP surface, not inside the decode loop
        if task.temperature is not None and not 0.0 <= task.temperature <= 10.0:
            return 400, json.dumps({
                "message": "temperature must be between 0 and 10",
                "task_id": task.task_id})
        if task.top_k is not None and task.top_k > 100_000:
            return 400, json.dumps({
                "message": "top_k must be at most 100000",
                "task_id": task.task_id})
        priority = (headers.get("x-symbiont-priority")
                    or "normal").strip().lower()
        _tenant, extra = self._edge_admit("generate", headers,
                                          priority=priority)
        with span("api.generate_text", self._trace_ctx(headers),
                  task_id=task.task_id) as sp:
            await self.bus.publish(subjects.TASKS_GENERATION_TEXT,
                                   to_json_bytes(task),
                                   headers={**sp.headers, **extra})
        self._gen_submitted[task.task_id] = True
        while len(self._gen_submitted) > 1024:
            self._gen_submitted.pop(next(iter(self._gen_submitted)))
        return 200, json.dumps({
            "message": f"Text generation task (id: {task.task_id}) submitted successfully.",
            "task_id": task.task_id})

    async def _graph_search(self, body: bytes,
                            headers: Dict[str, str]) -> Tuple[int, str]:
        """Graph-augmented search (the un-orphaned knowledge-graph limb as
        a first-class query surface): one request-reply hop to
        tasks.search.graph.request, same admission class and status
        mapping as semantic search."""
        data = json.loads(body)
        query_text = (data.get("query_text") or "").strip()
        if not query_text:
            return 400, json.dumps({"message": "query_text cannot be empty",
                                    "task_id": None})
        try:
            top_k = int(data.get("top_k", 5))
        except (TypeError, ValueError):
            # same 400-at-the-edge contract as semantic search — a
            # malformed field is the client's error, not a 500
            return 400, json.dumps({"message": "top_k must be an integer",
                                    "task_id": None})
        tenant, extra = self._edge_admit("search", headers)
        top_k, _ = self._degraded_top_k(tenant, top_k)
        async with self._search_slot(tenant):
            # billed only once the fair-queue slot is HELD: a queue_full
            # 429 is refused work and must not bill (same stance as quota)
            self._meter_search(tenant)
            with span("api.graph_search", self._trace_ctx(headers),
                      top_k=top_k) as sp:
                try:
                    reply = await self.bus.request(
                        subjects.TASKS_SEARCH_GRAPH_REQUEST,
                        json.dumps({"query_text": query_text,
                                    "top_k": top_k}).encode(),
                        timeout=_deadline_capped(
                            self.bus_config.request_timeout_search_s,
                            extra),
                        headers={**sp.headers, **extra})
                except TimeoutError as e:
                    return 503, json.dumps({
                        "results": [],
                        "error_message":
                            f"Failed to get graph search results "
                            f"from knowledge graph service: {e}"})
        try:
            out = json.loads(reply.data)
            if not isinstance(out, dict):
                raise ValueError("reply is not a JSON object")
        except ValueError as e:
            return 500, json.dumps({
                "results": [],
                "error_message": f"bad graph search reply: {e}"})
        return (500 if out.get("error_message") else 200), json.dumps(out)

    async def _semantic_search(self, body: bytes,
                               headers: Dict[str, str]) -> Tuple[int, str]:
        """2-hop orchestration with the reference's status mapping
        (main.rs:272-512): bus timeout → 503; service-reported error → 500.

        Overload plane: per-tenant quota + a weighted-fair concurrency slot
        around the whole orchestration (a hot tenant's backlog waits in ITS
        bounded queue, everyone else's requests keep flowing), and the shed
        ladder's degraded rung clamps top-k / skips rerank instead of
        failing the request outright."""
        req = from_dict(SemanticSearchApiRequest, json.loads(body))
        request_id = generate_uuid()
        tenant, extra = self._edge_admit("search", headers)
        req.top_k, degraded = self._degraded_top_k(tenant, req.top_k)
        if degraded and req.rerank:
            # degraded also skips the cross-encoder pass: answering
            # cheaper beats failing while the SLO recovers
            req.rerank = False
        async with self._search_slot(tenant):
            # billed only once the fair-queue slot is HELD: a queue_full
            # 429 is refused work and must not bill (same stance as quota)
            self._meter_search(tenant)
            return await self._semantic_search_inner(req, request_id,
                                                     headers, extra)

    async def _semantic_search_inner(self, req, request_id: str,
                                     headers: Dict[str, str],
                                     extra: Dict[str, str]) -> Tuple[int, str]:
        def resp(results, err=None) -> str:
            return to_json(SemanticSearchApiResponse(
                search_request_id=request_id, results=results,
                error_message=err))

        with span("api.search", self._trace_ctx(headers),
                  top_k=req.top_k) as sp:
            # downstream hops publish under THIS span's context so their
            # handler spans link into the search trace; deadline + tenant
            # thread along with it
            trace = {**sp.headers, **extra}
            if self.config.fused_search:
                fused = await self._fused_search(req, trace)
                if fused is not None:
                    results, err = fused
                    if err is not None:
                        return 500, resp([], err)
                    if req.rerank and results:
                        return await self._apply_rerank(req, results, resp, trace)
                    return 200, resp(results)
                # fused subject unserved / malformed reply → 2-hop fallback
            embed_task = QueryForEmbeddingTask(request_id=request_id,
                                               text_to_embed=req.query_text)
            try:
                # frame-negotiated reply (schema/frames): the accept HEADER
                # keeps the request body byte-identical for reference-era
                # preprocessing peers, which simply ignore it and answer
                # JSON float lists — both reply forms are decoded below
                reply = await self.bus.request(
                    subjects.TASKS_EMBEDDING_FOR_QUERY,
                    to_json_bytes(embed_task),
                    timeout=_deadline_capped(
                        self.bus_config.request_timeout_embed_s, trace),
                    headers={**trace, frames.ACCEPT_FRAME_HEADER: "1"})
            except TimeoutError as e:
                return 503, resp([], f"Failed to get embedding from preprocessing service: {e}")
            reply_json, reply_rows = frames.detach_frame(reply.data,
                                                         reply.headers)
            embed_result = from_json(QueryEmbeddingResult, reply_json)
            if embed_result.error_message:
                return 500, resp([], embed_result.error_message)
            query_embedding = (reply_rows[0].tolist()
                               if reply_rows is not None and len(reply_rows)
                               else embed_result.embedding)
            if not query_embedding:
                # None OR empty: `embedding: []` is a legal frame-mode body,
                # so a reply whose frame went missing must fail clean here,
                # not as an opaque store shape error two hops later
                return 500, resp([], "embedding service returned no embedding")

            search_task = SemanticSearchNatsTask(
                request_id=request_id,
                query_embedding=query_embedding,
                top_k=req.top_k)
            try:
                reply = await self.bus.request(
                    subjects.TASKS_SEARCH_SEMANTIC_REQUEST,
                    to_json_bytes(search_task),
                    timeout=_deadline_capped(
                        self.bus_config.request_timeout_search_s, trace),
                    headers=trace)
            except TimeoutError as e:
                return 503, resp([], f"Failed to get search results from vector memory service: {e}")
            search_result = from_json(SemanticSearchNatsResult, reply.data)
            if search_result.error_message:
                return 500, resp([], search_result.error_message)
            results = search_result.results
            if req.rerank and results:
                return await self._apply_rerank(req, results, resp, trace)
            return 200, resp(results)

    # ------------------------------------------------------------------ DLQ

    def _dlq_store(self):
        """The bus-attached dead-letter quarantine (inproc durable layer).
        On broker transports quarantine lives broker-side; this surface
        reports unavailable rather than pretending it is empty."""
        return getattr(self.bus, "dlq", None)

    def _dlq_list(self) -> Tuple[int, str]:
        store = self._dlq_store()
        if store is None:
            return 200, json.dumps({
                "available": False, "size": 0, "entries": [],
                "message": ("no in-process DLQ on this bus transport — "
                            "dead letters are accounted broker-side "
                            "(stream_stats dead_lettered)")})
        return 200, json.dumps({
            "available": True, "size": len(store),
            "entries": [e.summary() for e in store.list()]})

    async def _dlq_replay(self, body: bytes) -> Tuple[int, str]:
        """Replay quarantined message(s) to their original subject —
        body {"id": N} for one entry, {"all": true} for everything. The
        replayed message re-enters the durable flow with a fresh delivery
        budget (fix the handler first)."""
        store = self._dlq_store()
        if store is None:
            return 503, json.dumps(
                {"message": "no in-process DLQ on this bus transport",
                 "replayed": 0})
        data = json.loads(body) if body else {}
        entry_id = data.get("id")
        if entry_id is None and not data.get("all"):
            return 400, json.dumps(
                {"message": 'pass {"id": N} or {"all": true}',
                 "replayed": 0})
        if entry_id is not None and not isinstance(entry_id, int):
            return 400, json.dumps(
                {"message": "id must be an integer", "replayed": 0})
        replayed = await store.replay(self.bus, entry_id)
        if entry_id is not None and replayed == 0:
            return 404, json.dumps(
                {"message": f"no DLQ entry {entry_id} (already replayed or "
                            "evicted)", "replayed": 0})
        return 200, json.dumps({"replayed": replayed})

    async def _engine_health(self) -> Tuple[int, str]:
        """Engine-plane health over HTTP: one bus round-trip to
        engine.health (backends map, model, stats, vector count) so
        operators see the whole deployment from the gateway. 503 when no
        engine plane answers."""
        try:
            reply = await self.bus.request(
                subjects.ENGINE_HEALTH, b"{}",
                timeout=self.bus_config.request_timeout_health_s,
                headers=new_trace_headers())
        except TimeoutError:
            return 503, json.dumps(
                {"ok": False, "error_message": "engine plane unreachable"})
        try:
            body = json.loads(reply.data)
            if not isinstance(body, dict):
                raise ValueError("not an object")
        except ValueError as e:
            return 500, json.dumps(
                {"ok": False, "error_message": f"bad engine health reply: {e}"})
        if body.get("error_message"):
            # the health op itself failed (e.g. external store down) — a
            # status-based monitor must see that as unhealthy, not 200
            body.setdefault("ok", False)
            return 500, json.dumps(body)
        return 200, json.dumps(body)

    async def _fused_search(self, req: SemanticSearchApiRequest, trace):
        """Try the fused embed+top-k engine hop (one device round-trip).
        Returns (results, error) on a served reply, or None to signal
        fallback to the 2-hop orchestration (subject unserved within the
        short timeout, or malformed reply). A timeout negative-caches the
        subject for fused_search_down_s so a deployment without a co-located
        engine+store pays the probe once per window, not per request."""
        import time as _time

        from symbiont_tpu.schema import QdrantPointPayload, SemanticSearchResultItem

        if _time.monotonic() < self._fused_down_until:
            return None
        if req.top_k > self.config.fused_search_max_top_k:
            # fused executables are pre-warmed for the k≤16 buckets only; a
            # larger k would pay a cold XLA compile inside the probe timeout
            # AND trip the negative cache for everyone — take the 2-hop path
            metrics.inc("api.fused_search_skipped_large_k")
            return None
        try:
            reply = await self.bus.request(
                subjects.ENGINE_QUERY_SEARCH,
                json.dumps({"text": req.query_text,
                            "top_k": req.top_k}).encode(),
                timeout=self.config.fused_search_timeout_s,
                headers=trace)
        except TimeoutError:
            self._fused_down_until = (_time.monotonic()
                                      + self.config.fused_search_down_s)
            metrics.inc("api.fused_search_fallback")
            return None
        try:
            rr = json.loads(reply.data)
            if not isinstance(rr, dict):
                raise ValueError("reply is not a JSON object")
            if rr.get("error_message"):
                return [], rr["error_message"]
            results = [
                SemanticSearchResultItem(
                    qdrant_point_id=h["id"], score=float(h["score"]),
                    payload=QdrantPointPayload(**h["payload"]))
                for h in rr["hits"]
            ]
            metrics.inc("api.fused_search")
            return results, None
        except (ValueError, TypeError, KeyError) as e:
            log.warning("bad fused-search reply (%s); falling back to 2-hop", e)
            metrics.inc("api.fused_search_fallback")
            return None

    async def _apply_rerank(self, req, results, resp, trace) -> Tuple[int, str]:
        """Third hop (our addition, BASELINE.md #4): cross-encoder rerank of
        the top-k hits; scores become CE relevance logits."""
        rerank_req = {"query": req.query_text,
                      "passages": [r.payload.sentence_text for r in results]}
        try:
            reply = await self.bus.request(
                subjects.ENGINE_RERANK,
                json.dumps(rerank_req).encode(),
                timeout=self.bus_config.request_timeout_rerank_s,
                headers=trace)
        except TimeoutError as e:
            return 503, resp([], f"Failed to get rerank scores from engine service: {e}")
        try:
            rr = json.loads(reply.data)
            if not isinstance(rr, dict):
                raise ValueError("reply is not a JSON object")
            if rr.get("error_message"):
                return 500, resp([], rr["error_message"])
            scores = rr.get("scores")
            if not isinstance(scores, list) or len(scores) != len(results):
                # C++ twin parity (api_gateway.cpp): a short score list
                # must not silently mix cosine and CE scales
                raise ValueError("score count mismatch")
            for r, s in zip(results, scores):
                r.score = float(s)
        except (ValueError, TypeError) as e:
            return 500, resp([], f"bad rerank reply: {e}")
        results = sorted(results, key=lambda r: r.score, reverse=True)
        return 200, resp(results)

    # ------------------------------------------------------------------ SSE

    async def _serve_sse(self, writer, headers: Dict[str, str],
                         query: str = "") -> None:
        """SSE with 15s keep-alive comments (reference: main.rs:190-213).
        ?task_id=<id> opts into per-task routing (see _SseHub)."""
        from urllib.parse import parse_qs

        origin = headers.get("origin")
        head = ("HTTP/1.1 200 OK\r\n"
                "Content-Type: text/event-stream\r\n"
                "Cache-Control: no-cache\r\n"
                f"{self._cors(origin)}"
                "Connection: keep-alive\r\n\r\n")
        writer.write(head.encode("latin-1"))
        await writer.drain()
        task_filter = (parse_qs(query).get("task_id") or [None])[0] or None
        client = self.hub.register(task_filter,
                                   headers.get("last-event-id"))
        q = client.q
        # live-connection GAUGE (decremented on disconnect below) plus a
        # cumulative counter — the pre-obs `api.sse_clients` counter only
        # ever incremented, so it silently read as "clients currently
        # connected" while actually counting connects-ever
        metrics.gauge_add("api.sse_clients", 1)
        metrics.inc("api.sse_clients_total")
        shutdown = False
        completed = False  # saw the task's done-chunk / final message
        try:
            while True:
                try:
                    item = await asyncio.wait_for(
                        q.get(), timeout=self.config.sse_keepalive_s)
                    if item is None:  # close sentinel from stop()
                        shutdown = True
                        return
                    if item is _LAGGED:
                        # this client fell behind the broadcast and has a
                        # gap: close EXPLICITLY with a retry hint so it
                        # reconnects (Last-Event-ID replays what history
                        # still holds) instead of silently serving
                        # truncated text
                        metrics.inc("api.sse_lagged_closed")
                        writer.write(b"retry: 1000\n"
                                     b"event: error\n"
                                     b'data: {"error": "client lagged; '
                                     b'reconnect to resume"}\n\n')
                        await writer.drain()
                        return
                    payload, sse_id, done = item
                    if task_filter and done:
                        completed = True
                    if sse_id:
                        # SSE event id → browsers echo it back as
                        # Last-Event-ID on auto-reconnect
                        writer.write(f"id: {sse_id}\n".encode("utf-8"))
                    for line in payload.splitlines() or [""]:
                        writer.write(f"data: {line}\n".encode("utf-8"))
                    writer.write(b"\n")
                except asyncio.TimeoutError:
                    writer.write(b": keep-alive\n\n")
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, ConnectionAbortedError):
            pass
        finally:
            self.hub.unregister(client)
            metrics.gauge_add("api.sse_clients", -1)
            if (task_filter and not shutdown and not completed
                    and task_filter in self._gen_submitted
                    and not self.hub.has_follower(task_filter)):
                # the LAST reader of a task this gateway accepted vanished
                # MID-generation: tell the text generator so the task's
                # decode row frees at the next chunk boundary instead of
                # pinning a KV slot to budget exhaustion. A normal close
                # after the done event, a never-submitted task id, or a
                # surviving co-reader all publish nothing — the generator
                # tombstones unknown ids (the cancel-raced-ahead case), so
                # a spurious cancel would kill a live or future stream.
                metrics.inc("api.sse_gen_cancels")
                try:
                    await self.bus.publish(
                        subjects.TASKS_GENERATION_CANCEL,
                        json.dumps({"task_id": task_filter}).encode())
                except Exception:
                    log.debug("generation cancel publish failed",
                              exc_info=True)


def to_json_bytes_url(url: str) -> bytes:
    from symbiont_tpu.schema import PerceiveUrlTask

    return to_json_bytes(PerceiveUrlTask(url=url))


_FRONTEND_CACHE: list = []  # [Optional[str]] — loaded once, like the C++ twin


def _frontend_html() -> Optional[str]:
    """The bundled single-page UI (frontend/index.html), if present.

    SYMBIONT_FRONTEND_PATH overrides; falling back to the repo-layout location
    next to the package. Loaded once at first use (blocking disk I/O must not
    ride the event loop per request). Returns None when not found — the
    gateway then 404s; it never fails to start (the API is fully usable
    without the UI, same as the reference where the frontend is a separate
    container, docker-compose.yml:131-145)."""
    if _FRONTEND_CACHE:
        return _FRONTEND_CACHE[0]
    import os
    from pathlib import Path

    override = os.environ.get("SYMBIONT_FRONTEND_PATH")
    candidates = ([Path(override)] if override else []) + [
        Path(__file__).resolve().parents[2] / "frontend" / "index.html"]
    html = None
    for p in candidates:
        try:
            html = p.read_text(encoding="utf-8")
            break
        except OSError:
            continue
    _FRONTEND_CACHE.append(html)
    return html
