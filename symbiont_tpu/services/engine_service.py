"""Engine service — the TPU-owning process's bus frontend.

This is the "sun" of the architecture (SURVEY.md §7 design stance): exactly one
process owns the device (engine + LM + vector store + graph store), and every
other worker — Python or native C++ — reaches compute and storage through
request-reply on the `engine.*` subjects. The reference's equivalent decision
was to put candle *inside* preprocessing_service (reference:
services/preprocessing_service/src/embedding_generator.rs:9-14), which couples
every scale-out of the bus workers to a GPU context and creates the
concurrent-forward hazard SURVEY.md §5.2 documents. Splitting the plane here
means:

- native C++ shells (native/services/*.cpp) carry the bus/schema/business
  logic with zero Python in-process;
- all callers share ONE micro-batching queue in front of the device, so
  interactive queries and bulk ingest coexist (SURVEY.md §7 hard part #4);
- engine restart does not restart the pipeline workers (two-plane failure
  semantics, §7 hard part #6).

Payloads on this plane are plain JSON (framework-internal; the reference wire
schema from SURVEY.md §1-L3 is untouched). Every reply carries
`error_message: null | str` — the typed-error-reply convention the reference
uses on its request-reply paths (reference:
services/preprocessing_service/src/main.rs:183-196).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

import numpy as np

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.engine.batcher import MicroBatcher
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.schema import TokenizedTextMessage, from_dict
from symbiont_tpu.schema import frames
from symbiont_tpu.resilience import admission
from symbiont_tpu.services.base import Service
from symbiont_tpu.services.coalesce import (
    UpsertCoalescer,
    store_executor,
    upsert_rows_or_points,
)
from symbiont_tpu.utils.telemetry import child_headers, metrics, span

log = logging.getLogger(__name__)

# request/reply key carrying a decoded tensor frame through the op plumbing
# (never serialized: _handle pops it off the wire, _reply re-attaches it)
_FRAME_KEY = "_frame"
# sibling key: the wire dtype the op chose for its reply frame (defaults to
# the full-width form when absent)
_FRAME_DTYPE_KEY = "_frame_dtype"


def _err(payload: dict) -> bytes:
    payload.setdefault("error_message", None)
    # compact separators (matching schema.to_json): every engine reply used
    # to carry json.dumps' default ", "/": " whitespace — pure wasted bytes
    # on the hottest reply path of the stack
    return json.dumps(payload, separators=(",", ":")).encode()


class EngineService(Service):
    name = "engine"

    def __init__(self, bus, engine: Optional[TpuEngine] = None,
                 batcher: Optional[MicroBatcher] = None, lm=None,
                 lm_batcher=None, vector_store=None, graph_store=None,
                 coalesce: bool = True, coalesce_max_rows: int = 512,
                 coalesce_max_age_ms: float = 25.0):
        super().__init__(bus)
        self.engine = engine
        self.batcher = batcher or (MicroBatcher(engine) if engine else None)
        self.lm = lm
        self.lm_batcher = lm_batcher
        self.vector_store = vector_store
        self.graph_store = graph_store
        self._warm_task: Optional[asyncio.Task] = None
        self._warm_failed = False  # last warm errored → next upsert retries
        # cross-REQUEST upsert coalescing (services/coalesce.py): the native
        # vector_memory shells each batch points per request, but N workers
        # × M in-flight requests still cost one store call (WAL fsync +
        # lock round-trip) each — here they merge into one. The reply to
        # each request is held until the flush carrying its rows commits,
        # so the shells' ack-after-reply contract is ack-after-flush
        # end to end.
        self._upsert_coalescer: Optional[UpsertCoalescer] = (
            UpsertCoalescer(self._store_upsert_rows,
                            max_rows=coalesce_max_rows,
                            max_age_ms=coalesce_max_age_ms, name=self.name)
            if coalesce and vector_store is not None else None)

    def _store_upsert_rows(self, ids, rows, payloads) -> int:
        return upsert_rows_or_points(self.vector_store, ids, rows, payloads)

    async def start(self) -> None:
        if self.batcher:
            await self.batcher.start()
        if self._upsert_coalescer is not None:
            await self._upsert_coalescer.start()
        await super().start()
        self._spawn_fused_warm()

    def _fused_enabled(self) -> bool:
        return (self.engine is not None and self.vector_store is not None
                and getattr(self.vector_store, "supports_fused", False))

    def _spawn_fused_warm(self) -> None:
        """Background-compile the fused query executables for the store's
        current capacity across the query length buckets (works for an empty
        store too — capacity is the first block), so interactive queries
        don't eat the 20-40s TPU compile inside the gateway's probe timeout.
        Queries arriving mid-warmup fall back to the 2-hop path; the store
        lock is never held across a compile. Re-invoked when upserts cross a
        capacity block (the executables are capacity-keyed)."""
        if not self._fused_enabled():
            return
        if self._warm_task is not None and not self._warm_task.done():
            return  # one warmup at a time; stale check re-fires after it
        self._warm_failed = False

        async def warm() -> None:
            loop = asyncio.get_running_loop()
            try:
                await loop.run_in_executor(
                    None, self.vector_store.warm_fused, self.engine)
                log.info("fused query executables warmed")
            except Exception:
                log.exception("fused warmup failed (non-fatal)")
                self._warm_failed = True  # next upsert retries
                return
            # an upsert may have crossed a capacity block while this warm
            # was compiling (spawn attempts during a live warm are no-ops) —
            # re-check so the stale window closes without waiting for the
            # next upsert. Executor: the staleness check takes the store
            # lock, which a concurrent device sync can hold for a while.
            if await loop.run_in_executor(
                    None, self.vector_store.fused_warm_stale):
                self._warm_task = None
                self._spawn_fused_warm()

        self._warm_task = asyncio.create_task(warm(), name="fused-warmup")

    async def drain(self) -> None:
        # drain protocol (resilience/autoscale.py): immediate-flush mode
        # first so in-flight upsert requests' reply-after-flush waits
        # resolve without the age window — see VectorMemoryService.drain
        if self._upsert_coalescer is not None:
            self._upsert_coalescer.drain_mode()
        await super().drain()

    async def stop(self) -> None:
        if self._warm_task is not None:
            self._warm_task.cancel()
        await super().stop()
        if self._upsert_coalescer is not None:
            await self._upsert_coalescer.stop()
        if self.batcher:
            await self.batcher.close()

    async def _setup(self) -> None:
        q = subjects.QUEUE_ENGINE
        sub = self._subscribe_loop
        if self.engine is not None:
            await sub(subjects.ENGINE_EMBED_BATCH, self._embed_batch, queue=q)
            await sub(subjects.ENGINE_EMBED_QUERY, self._embed_query, queue=q)
            # subscribed even without a cross-encoder: a rerank request against
            # a rerank-disabled stack must get a fast typed error reply
            # ("no cross-encoder model loaded"), not a 10s caller timeout
            await sub(subjects.ENGINE_RERANK, self._rerank, queue=q)
        if self.lm is not None:
            await sub(subjects.ENGINE_GENERATE, self._generate, queue=q)
        if self.vector_store is not None:
            await sub(subjects.ENGINE_VECTOR_UPSERT, self._vec_upsert, queue=q)
            await sub(subjects.ENGINE_VECTOR_SEARCH, self._vec_search, queue=q)
        if (self.engine is not None and self.vector_store is not None
                and getattr(self.vector_store, "supports_fused", False)):
            # fused embed+top-k — only when this process holds both the model
            # and a device-resident corpus (external Qdrant backends don't)
            await sub(subjects.ENGINE_QUERY_SEARCH, self._query_search, queue=q)
        if self.graph_store is not None:
            await sub(subjects.ENGINE_GRAPH_SAVE, self._graph_save, queue=q)
        await sub(subjects.ENGINE_HEALTH, self._health, queue=q)

    # ------------------------------------------------------------- plumbing

    async def _reply(self, msg: Msg, payload: dict) -> None:
        if not msg.reply:
            return
        headers = child_headers(msg.headers)
        # an op that put an ndarray under _FRAME_KEY replies with the block
        # as a binary tensor frame appended to the JSON metadata, in the
        # wire dtype the op negotiated (_FRAME_DTYPE_KEY)
        frame = payload.pop(_FRAME_KEY, None)
        dtype = payload.pop(_FRAME_DTYPE_KEY, None)
        data = _err(payload)
        if frame is not None:
            data, fheaders = (frames.attach_frame(data, frame, dtype=dtype)
                              if dtype is not None
                              else frames.attach_frame(data, frame))
            headers.update(fheaders)
        await self.bus.publish(msg.reply, data, headers=headers)

    async def _handle(self, msg: Msg, op: str, fn) -> None:
        """Decode → run op → reply; typed error reply on any failure.
        A request-side tensor frame (schema/frames) is detached here and
        handed to the op as `req["_frame"]` (a zero-copy [n, dim] view)."""
        if not msg.reply:
            log.warning("engine op %s without reply inbox dropped", op)
            metrics.inc("engine.no_reply_inbox")
            return
        try:
            raw, frame = frames.detach_frame(msg.data or b"", msg.headers)
            req = json.loads(raw) if raw else {}
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
            req.pop(_FRAME_KEY, None)  # reserved: only a real frame sets it
            if frame is not None:
                req[_FRAME_KEY] = frame
        except Exception as e:
            await self._reply(msg, {"error_message": f"bad request: {e}"})
            return
        try:
            with span(f"engine.{op}", msg.headers):
                payload = await fn(req)
            metrics.inc(f"engine.{op}")
        except Exception as e:
            log.exception("engine op %s failed", op)
            metrics.inc(f"engine.{op}.failed")
            payload = {"error_message": str(e)}
        await self._reply(msg, payload)

    async def _run_blocking(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    async def _run_store(self, fn, *args):
        """Blocking vector-store WRITES ride the dedicated bounded store
        executor (services/coalesce.py): a WAL fsync or breaker-degraded
        upsert must not steal default-pool threads from the embed forwards
        running concurrently. Reads (search/count) stay on the default
        pool — the latency path must not queue behind a bulk flush."""
        return await asyncio.get_running_loop().run_in_executor(
            store_executor(), fn, *args)

    # ------------------------------------------------------------- compute

    async def _embed_batch(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            texts = req["texts"]
            if not isinstance(texts, list) or not all(isinstance(t, str) for t in texts):
                raise ValueError("texts must be a list of strings")
            # fairness lane from the bus tenant header (native shells thread
            # it verbatim via child_headers — common.hpp parity)
            vecs = await self.batcher.embed(
                texts, tenant=admission.tenant_of(msg.headers))
            encoding = req.get("encoding")
            if encoding in ("frame", "frame16"):
                # zero-copy reply for frame-capable callers: the [n, dim]
                # block rides as a binary tensor frame appended to the JSON
                # metadata (_reply attaches it; schema/frames). encoding
                # frame16 asks for the half-width dtype-2 form — the ONE
                # place a service maps a negotiated encoding to a frame
                # dtype (allowlisted in tests/test_pipeline_wiring.py; every
                # other dtype decision lives in schema/frames.py). An old
                # engine ignores either encoding value and answers with
                # JSON float lists — the fallback every caller accepts.
                arr = np.ascontiguousarray(np.asarray(vecs, np.float32))
                if arr.ndim == 1:  # zero texts edge: keep the 2-D contract
                    arr = arr.reshape(0, 0)
                return {"count": int(arr.shape[0]), "dim": int(arr.shape[1]),
                        "model_name": self.engine.config.model_name,
                        _FRAME_KEY: arr,
                        _FRAME_DTYPE_KEY: ("f16" if encoding == "frame16"
                                           else "f32")}
            if encoding == "b64":
                # compact reply for reference-era bulk callers: f32
                # little-endian rows base64'd is ~4.3 bytes per float vs
                # ~10 digits of JSON
                import base64

                arr = np.ascontiguousarray(np.asarray(vecs, np.float32))
                if arr.ndim == 1:  # zero texts edge: keep the 2-D contract
                    arr = arr.reshape(0, 0)
                return {"vectors_b64": base64.b64encode(arr.tobytes()).decode(
                            "ascii"),
                        "count": int(arr.shape[0]), "dim": int(arr.shape[1]),
                        "model_name": self.engine.config.model_name}
            # JSON fallback: ndarray.tolist() converts in C (no per-float
            # Python loop), same double-widened digits as before
            return {"vectors": np.asarray(vecs, np.float32).tolist(),
                    "model_name": self.engine.config.model_name}
        await self._handle(msg, "embed.batch", op)

    async def _embed_query(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            text = req["text"]
            if not isinstance(text, str):
                raise ValueError("text must be a string")
            # interactive lane: never FIFO a query behind the same
            # tenant's bulk backlog (see preprocessing._handle_query_
            # embedding; load_ramp measured the starvation)
            from symbiont_tpu.engine.batcher import interactive_lane

            vecs = await self.batcher.embed(
                [text],
                tenant=interactive_lane(admission.tenant_of(msg.headers)))
            return {"vector": np.asarray(vecs[0], np.float32).tolist(),
                    "model_name": self.engine.config.model_name}
        await self._handle(msg, "embed.query", op)

    async def _rerank(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            scores = await self._run_blocking(
                self.engine.rerank, req["query"], req["passages"])
            return {"scores": [float(s) for s in scores]}
        await self._handle(msg, "rerank", op)

    async def _generate(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            prompt = req.get("prompt") or ""
            max_new = int(req.get("max_new_tokens", 50))
            temperature = req.get("temperature")
            temperature = None if temperature is None else float(temperature)
            top_k = req.get("top_k")
            top_k = None if top_k is None else int(top_k)
            if self.lm_batcher is not None:
                # shared micro-batcher: concurrent engine.generate callers
                # decode as one batch with the bus-surface requests
                text = await self.lm_batcher.generate(
                    prompt, max_new, temperature=temperature, top_k=top_k,
                    tenant=admission.tenant_of(msg.headers))
            else:
                text = await self._run_blocking(
                    lambda: self.lm.generate(prompt, max_new,
                                             temperature=temperature,
                                             top_k=top_k))
            name = self.lm.config.model_dir or f"symbiont-lm/{self.lm.config.arch}"
            return {"text": text, "model_name": name}
        await self._handle(msg, "generate", op)

    # ------------------------------------------------------------- storage

    async def _vec_upsert(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            rows = None
            if _FRAME_KEY in req:
                # tensor-frame ingest (the C++ vector_memory shell's frame
                # hop): the [n, dim] block arrived as a zero-copy view —
                # it goes into the store without touching JSON floats
                rows = req[_FRAME_KEY]
                ids = req["ids"]
                if rows.shape[0] != len(ids):
                    raise ValueError(
                        f"frame holds {rows.shape[0]} rows for "
                        f"{len(ids)} ids")
                if "dim" in req and rows.shape[1] != int(req["dim"]):
                    raise ValueError(
                        f"frame dim {rows.shape[1]} != declared "
                        f"dim {req['dim']}")
                payloads = req.get("payloads") or [{}] * len(ids)
                if len(payloads) != len(ids):
                    raise ValueError(
                        f"{len(payloads)} payloads for {len(ids)} ids")
            elif "vectors_b64" in req:
                # compact form from reference-era C++ shells: all vectors
                # in one base64 f32 block (framework-internal plane; the
                # data.text.with_embeddings wire schema is untouched)
                import base64

                dim = int(req["dim"])
                flat = np.frombuffer(base64.b64decode(req["vectors_b64"]),
                                     dtype=np.float32)
                ids = req["ids"]
                if dim <= 0 or flat.size != len(ids) * dim:
                    raise ValueError(
                        f"vectors_b64 holds {flat.size} floats for "
                        f"{len(ids)} ids of dim {dim}")
                rows = flat.reshape(len(ids), dim)
                payloads = req.get("payloads") or [{}] * len(ids)
                if len(payloads) != len(ids):
                    # zip would silently truncate and drop points
                    raise ValueError(
                        f"{len(payloads)} payloads for {len(ids)} ids")
            else:
                points = [(p["id"], p["vector"], p.get("payload", {}))
                          for p in req["points"]]
            if rows is not None:
                if self._upsert_coalescer is not None:
                    # reply-after-flush: resolves once the coalesced store
                    # call carrying THESE rows committed; a flush failure
                    # surfaces as this request's typed error reply
                    n = await self._upsert_coalescer.add(ids, rows, payloads,
                                                         headers=msg.headers)
                else:
                    n = await self._run_store(
                        self._store_upsert_rows, ids, rows, payloads)
            else:
                # legacy per-point JSON form (reference-era callers): rare
                # and small — straight through, no coalescing
                n = await self._run_store(self.vector_store.upsert,
                                          points)
            if self._fused_enabled() and (
                    self._warm_failed or await self._run_store(
                        self.vector_store.fused_warm_stale)):
                # upserts crossed a capacity block (or the last warm failed):
                # the fused executables are keyed by capacity, so the next
                # query would pay a fresh XLA compile — re-warm in the
                # background before it arrives. Executor: the staleness check
                # takes the store lock (see _spawn_fused_warm)
                self._spawn_fused_warm()
            return {"upserted": n}
        await self._handle(msg, "vector.upsert", op)

    async def _vec_search(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            hits = await self._run_blocking(
                self.vector_store.search, req["vector"], int(req["top_k"]))
            return {"hits": [{"id": h.id, "score": float(h.score),
                              "payload": h.payload} for h in hits]}
        await self._handle(msg, "vector.search", op)

    async def _query_search(self, msg: Msg) -> None:
        """Fused interactive query: text → embed + cosine top-k in one device
        program (TpuEngine.embed_and_search). The latency path of SURVEY.md
        §3.2 collapsed to a single bus hop and a single device round-trip."""
        async def op(req: dict) -> dict:
            text = req["text"]
            if not isinstance(text, str):
                raise ValueError("text must be a string")
            hits = await self._run_blocking(
                self.vector_store.search_fused, self.engine, text,
                int(req["top_k"]))
            return {"hits": [{"id": h.id, "score": float(h.score),
                              "payload": h.payload} for h in hits],
                    "model_name": self.engine.config.model_name}
        await self._handle(msg, "query.search", op)

    async def _graph_save(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            m = from_dict(TokenizedTextMessage, req["message"])
            doc_id = await self._run_blocking(self.graph_store.save_tokenized, m)
            return {"document_db_id": doc_id}
        await self._handle(msg, "graph.save", op)

    # -------------------------------------------------------------- health

    async def _health(self, msg: Msg) -> None:
        async def op(req: dict) -> dict:
            out = {"ok": True, "backends": {
                "embed": self.engine is not None,
                "rerank": bool(self.engine is not None
                               and self.engine.cross_params is not None),
                "generate": self.lm is not None,
                "vector": self.vector_store is not None,
                "graph": self.graph_store is not None,
            }}
            if self.engine is not None:
                out["embedding_dim"] = self.engine.model_cfg.hidden_size
                out["model_name"] = self.engine.config.model_name
                out["stats"] = dict(self.engine.stats)
            if self.vector_store is not None:
                # executor: an external-Qdrant count is a blocking HTTP call
                out["vector_count"] = await self._run_blocking(
                    self.vector_store.count)
            return out
        await self._handle(msg, "health", op)
