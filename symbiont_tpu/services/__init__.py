"""Worker services — the orbit around the TPU engine.

Each reference worker (one Rust binary + NATS loop, SURVEY.md §1-L2) maps to a
service class here with the same subjects and payloads; the runner
(symbiont_tpu.runner) hosts any subset in one process over the in-proc bus, or
each can run against the native broker for multi-process deployments. Native
C++ counterparts for the bus-and-glue services live under native/.
"""
