"""Vector-memory service — bus adapter over the TPU-native vector store.

Parity with reference: services/vector_memory_service/src/main.rs:
- startup ensure_collection (main.rs:24-119);
- data.text.with_embeddings → one point per sentence, uuid ids, 6-field
  QdrantPointPayload (main.rs:121-228), ack-after-durable (wait=true, :196);
- tasks.search.semantic.request request-reply with typed error replies
  (main.rs:230-456).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.memory.vector_store import VectorStore
from symbiont_tpu.schema import (
    QdrantPointPayload,
    SemanticSearchNatsResult,
    SemanticSearchNatsTask,
    SemanticSearchResultItem,
    from_json,
    to_json_bytes,
)
from symbiont_tpu.schema import frames
from symbiont_tpu.services.base import Service
from symbiont_tpu.utils.ids import (
    current_timestamp_ms,
    deterministic_point_id,
)
from symbiont_tpu.utils.telemetry import child_headers, metrics, span

log = logging.getLogger(__name__)


class VectorMemoryService(Service):
    name = "vector_memory"

    def __init__(self, bus, store: VectorStore, durable_stream=None):
        super().__init__(bus)
        self.store = store
        self.durable_stream = durable_stream

    async def _setup(self) -> None:
        # startup ensure (reference: create/ensure collection, main.rs:24-119)
        # in an executor: with an external-Qdrant backend this is a blocking
        # HTTP retry loop that must not freeze the event loop
        await asyncio.get_running_loop().run_in_executor(
            None, self.store.ensure_collection)
        await self._subscribe_loop(subjects.DATA_TEXT_WITH_EMBEDDINGS,
                                   self._handle_upsert,
                                   queue=subjects.QUEUE_VECTOR_MEMORY,
                                   durable_stream=self.durable_stream)
        await self._subscribe_loop(subjects.TASKS_SEARCH_SEMANTIC_REQUEST,
                                   self._handle_search,
                                   queue=subjects.QUEUE_VECTOR_MEMORY)

    async def _handle_upsert(self, msg: Msg) -> None:
        # both wire forms (schema/frames): a frame-bearing message hands
        # back a zero-copy [n, dim] view; the JSON fallback carries float
        # lists in the message as the reference always did
        m, rows = frames.decode_embeddings_message(msg.data, msg.headers)
        now = current_timestamp_ms()
        ids, payloads = [], []
        for order, se in enumerate(m.embeddings_data):
            payload = QdrantPointPayload(
                original_document_id=m.original_id,
                source_url=m.source_url,
                sentence_text=se.sentence_text,
                sentence_order=order,
                model_name=m.model_name,
                processed_at_ms=now,
            )
            # content-derived id: durable redelivery overwrites the same
            # point instead of duplicating it (reference mints random uuids,
            # main.rs:142-177 — safe only at-most-once)
            ids.append(deterministic_point_id(m.original_id, order))
            payloads.append(dataclasses.asdict(payload))
        with span("vector_memory.upsert", msg.headers, points=len(ids)):
            # executor: with an external-Qdrant backend this is a blocking
            # HTTP call; it must not stall the event loop
            loop = asyncio.get_running_loop()
            if rows is not None and hasattr(self.store, "upsert_rows"):
                # frame → store as one ndarray block: no per-float Python
                # object between the engine's output and the store
                n = await loop.run_in_executor(
                    None, self.store.upsert_rows, ids, rows, payloads)
            elif rows is not None:
                # backend without the fast path (bare external Qdrant):
                # hand the zero-copy row views through the tuple surface
                points = list(zip(ids, rows, payloads))
                n = await loop.run_in_executor(None, self.store.upsert,
                                               points)
            else:
                points = [(pid, se.embedding, payload)
                          for pid, se, payload in
                          zip(ids, m.embeddings_data, payloads)]
                n = await loop.run_in_executor(None, self.store.upsert,
                                               points)
        metrics.inc("vector_memory.points_upserted", n)

    async def _handle_search(self, msg: Msg) -> None:
        if not msg.reply:
            log.warning("search task without reply inbox")
            return
        try:
            task = from_json(SemanticSearchNatsTask, msg.data)
        except Exception as e:
            err = SemanticSearchNatsResult(request_id="unknown", results=[],
                                           error_message=f"bad request: {e}")
            await self.bus.publish(msg.reply, to_json_bytes(err))
            return
        try:
            with span("vector_memory.search", msg.headers, top_k=task.top_k):
                hits = await asyncio.get_running_loop().run_in_executor(
                    None, self.store.search, task.query_embedding, task.top_k)
            results = [
                SemanticSearchResultItem(
                    qdrant_point_id=h.id, score=h.score,
                    payload=QdrantPointPayload(**h.payload))
                for h in hits
            ]
            result = SemanticSearchNatsResult(request_id=task.request_id,
                                              results=results, error_message=None)
        except Exception as e:
            log.exception("search failed")
            result = SemanticSearchNatsResult(request_id=task.request_id,
                                              results=[], error_message=str(e))
        await self.bus.publish(msg.reply, to_json_bytes(result),
                               headers=child_headers(msg.headers))
        metrics.inc("vector_memory.searches")
