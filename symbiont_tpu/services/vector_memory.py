"""Vector-memory service — bus adapter over the TPU-native vector store.

Parity with reference: services/vector_memory_service/src/main.rs:
- startup ensure_collection (main.rs:24-119);
- data.text.with_embeddings → one point per sentence, uuid ids, 6-field
  QdrantPointPayload (main.rs:121-228), ack-after-durable (wait=true, :196);
- tasks.search.semantic.request request-reply with typed error replies
  (main.rs:230-456).

Ingest hot path (ROADMAP item 3, the 5× host gap) — three departures from
the reference's per-message lockstep:
- ZERO-CHURN decode: frame-bearing messages go through
  `frames.decode_embeddings_lazy` (one json.loads + one zero-copy array
  view; no per-sentence dataclasses) and the store payload dicts are built
  directly — `dataclasses.asdict` is statically banned on this path
  (tests/test_pipeline_wiring.py). The dict keys ARE the 6-field
  QdrantPointPayload wire shape; test_store_wire_fixtures pins it.
- CROSS-MESSAGE coalescing (services/coalesce.py): rows from many messages
  land as ONE `upsert_rows` call; each durable delivery is acked only after
  the flush carrying its rows commits (ack-after-flush — a crashed flush
  redelivers every message it carried, and deterministic point ids make the
  retry idempotent).
- the store call runs on the dedicated bounded store executor, not the
  default pool the embed/tokenize stages share.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.memory.vector_store import VectorStore
from symbiont_tpu.schema import (
    QdrantPointPayload,
    SemanticSearchNatsResult,
    SemanticSearchNatsTask,
    SemanticSearchResultItem,
    from_json,
    to_json_bytes,
)
from symbiont_tpu.schema import frames
from symbiont_tpu.services.base import Service
from symbiont_tpu.services.coalesce import (
    UpsertCoalescer,
    store_executor,
    upsert_rows_or_points,
)
from symbiont_tpu.utils.ids import (
    current_timestamp_ms,
    deterministic_point_id,
)
from symbiont_tpu.utils.telemetry import child_headers, metrics, span

log = logging.getLogger(__name__)


class VectorMemoryService(Service):
    name = "vector_memory"

    def __init__(self, bus, store: VectorStore, durable_stream=None,
                 coalesce: bool = True, coalesce_max_rows: int = 512,
                 coalesce_max_age_ms: float = 25.0):
        super().__init__(bus)
        self.store = store
        self.durable_stream = durable_stream
        self._coalescer: Optional[UpsertCoalescer] = (
            UpsertCoalescer(self._store_upsert, max_rows=coalesce_max_rows,
                            max_age_ms=coalesce_max_age_ms,
                            name=self.name)
            if coalesce else None)

    async def start(self) -> None:
        if self._coalescer is not None:
            await self._coalescer.start()
        await super().start()

    async def drain(self) -> None:
        # drain protocol: flip the coalescer to immediate-flush FIRST, so
        # the in-flight handlers stop() waits on resolve their
        # ack-after-flush futures right away instead of waiting out a
        # long age window — then the shared stop path (detach durable
        # consumers → wait handlers → coalescer flush-on-stop) runs
        if self._coalescer is not None:
            self._coalescer.drain_mode()
        await super().drain()

    async def stop(self) -> None:
        # order matters: super().stop() drains in-flight handlers first
        # (their ack-waits resolve via the still-running age flush), THEN
        # the coalescer flush-on-stops anything that never hit a trigger
        await super().stop()
        if self._coalescer is not None:
            await self._coalescer.stop()

    async def _setup(self) -> None:
        # startup ensure (reference: create/ensure collection, main.rs:24-119)
        # in an executor: with an external-Qdrant backend this is a blocking
        # HTTP retry loop that must not freeze the event loop
        await asyncio.get_running_loop().run_in_executor(
            None, self.store.ensure_collection)
        await self._subscribe_loop(subjects.DATA_TEXT_WITH_EMBEDDINGS,
                                   self._handle_upsert,
                                   queue=subjects.QUEUE_VECTOR_MEMORY,
                                   durable_stream=self.durable_stream)
        await self._subscribe_loop(subjects.TASKS_SEARCH_SEMANTIC_REQUEST,
                                   self._handle_search,
                                   queue=subjects.QUEUE_VECTOR_MEMORY)
        # operational count surface: a multi-process deployment's driver
        # (bench/load.py --multiproc) verifies EXACT zero-loss ingest from
        # outside this process through one request-reply hop
        await self._subscribe_loop(subjects.TASKS_MEMORY_COUNT,
                                   self._handle_count,
                                   queue=subjects.QUEUE_VECTOR_MEMORY)

    def _store_upsert(self, ids, rows, payloads) -> int:
        return upsert_rows_or_points(self.store, ids, rows, payloads)

    async def _handle_upsert(self, msg: Msg) -> None:
        # both wire forms (schema/frames), zero-churn: scalar metadata +
        # sentence texts + ONE [n, dim] row block — no per-sentence
        # dataclass, no per-float Python object
        m = frames.decode_embeddings_lazy(msg.data, msg.headers)
        now = current_timestamp_ms()
        ids, payloads = [], []
        for order, sentence in enumerate(m.sentences):
            # content-derived id: durable redelivery (and a re-coalesced
            # flush retry) overwrites the same point instead of duplicating
            # it (reference mints random uuids, main.rs:142-177 — safe only
            # at-most-once)
            ids.append(deterministic_point_id(m.original_id, order))
            # direct dict build — the 6 QdrantPointPayload wire fields;
            # keep in lockstep with the schema dataclass (pinned by
            # tests/test_store_wire_fixtures.py)
            payloads.append({
                "original_document_id": m.original_id,
                "source_url": m.source_url,
                "sentence_text": sentence,
                "sentence_order": order,
                "model_name": m.model_name,
                "processed_at_ms": now,
            })
        with span("vector_memory.upsert", msg.headers, points=len(ids)):
            if self._coalescer is not None:
                # ack-after-flush: resolves once the coalesced store call
                # carrying THESE rows committed (or raises what it raised —
                # the delivery then stays unacked for redelivery)
                n = await self._coalescer.add(ids, m.rows, payloads,
                                              headers=msg.headers)
            else:
                n = await asyncio.get_running_loop().run_in_executor(
                    store_executor(), self._store_upsert, ids, m.rows,
                    payloads)
        metrics.inc("vector_memory.points_upserted", n)

    async def _handle_count(self, msg: Msg) -> None:
        import json as _json

        if not msg.reply:
            return
        try:
            # executor: an external-Qdrant count is a blocking HTTP call
            n = await asyncio.get_running_loop().run_in_executor(
                None, self.store.count)
            payload = {"count": int(n), "error_message": None}
        except Exception as e:
            log.exception("count failed")
            payload = {"count": None, "error_message": str(e)}
        await self.bus.publish(msg.reply,
                               _json.dumps(payload).encode(),
                               headers=child_headers(msg.headers))

    async def _handle_search(self, msg: Msg) -> None:
        if not msg.reply:
            log.warning("search task without reply inbox")
            return
        try:
            task = from_json(SemanticSearchNatsTask, msg.data)
        except Exception as e:
            err = SemanticSearchNatsResult(request_id="unknown", results=[],
                                           error_message=f"bad request: {e}")
            await self.bus.publish(msg.reply, to_json_bytes(err))
            return
        try:
            # default pool, NOT the store executor: search is the latency
            # path and must never queue behind a bulk flush holding one of
            # the write pool's workers
            with span("vector_memory.search", msg.headers, top_k=task.top_k):
                hits = await asyncio.get_running_loop().run_in_executor(
                    None, self.store.search,
                    task.query_embedding, task.top_k)
            results = [
                SemanticSearchResultItem(
                    qdrant_point_id=h.id, score=h.score,
                    payload=QdrantPointPayload(**h.payload))
                for h in hits
            ]
            result = SemanticSearchNatsResult(request_id=task.request_id,
                                              results=results, error_message=None)
        except Exception as e:
            log.exception("search failed")
            result = SemanticSearchNatsResult(request_id=task.request_id,
                                              results=[], error_message=str(e))
        await self.bus.publish(msg.reply, to_json_bytes(result),
                               headers=child_headers(msg.headers))
        metrics.inc("vector_memory.searches")
