"""Text-generator service.

Parity with reference: services/text_generator_service/src/main.rs:111-162:
consumes GenerateTextTask, generates, publishes GeneratedTextMessage to
events.text.generated. Two backends:

- Markov (default, reference parity) — but trained continuously on every
  ingested document (the reference trains once on one hardcoded sentence and
  ignores the prompt, main.rs:120-123,169-174);
- TPU LM (optional, BASELINE.md config #5): decoder LM via models/gpt with
  the prompt actually used.
"""

from __future__ import annotations

import asyncio
import logging

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.kv.pool import PoolExhausted
from symbiont_tpu.models.markov import MarkovModel
from symbiont_tpu.schema import (
    GeneratedTextChunk,
    GeneratedTextMessage,
    GenerateTextTask,
    RawTextMessage,
    from_json,
    to_json_bytes,
)
from symbiont_tpu.resilience import admission
from symbiont_tpu.services.base import Service
from symbiont_tpu.utils.ids import current_timestamp_ms
from symbiont_tpu.utils.telemetry import child_headers, metrics, span

log = logging.getLogger(__name__)

# the reference's single hardcoded training sentence (main.rs:170) — kept as
# the cold-start corpus so an empty system still generates
SEED_CORPUS = (
    "Это первое предложение для обучения нашей марковской модели оно простое"
)


class TextGeneratorService(Service):
    name = "text_generator"

    def __init__(self, bus, lm_generate=None, lm_batcher=None, lm_stream=None,
                 train_on_ingest: bool = True, state_path=None,
                 lm_trainer=None, lm_train_min_chars: int = 512,
                 lm_train_steps: int = 2, lm_buffer_max_chars: int = 1 << 20,
                 journal=None, lm_resume=None,
                 resume_max_attempts: int = 5,
                 resume_backoff_s: float = 0.25):
        super().__init__(bus)
        # persistence (SURVEY.md §5.4): restore the learned chain; the
        # reference rebuilds from one constant at every boot (main.rs:169-173)
        self._state_path = state_path
        self._dirty = False
        self._last_save = 0.0
        restored = self._load_state()
        if restored is not None:
            self.markov = restored  # seed transitions already in the chain —
            # re-training them would double-count into the multiset weights
        else:
            self.markov = MarkovModel()
            self.markov.train(SEED_CORPUS)
        self.lm_generate = lm_generate  # (prompt, max_new, *, temperature=,
        #                                  top_k=) -> str | None
        #                                 (LmEngine.generate's signature)
        self.lm_batcher = lm_batcher  # GenBatcher | None (batches concurrent
        #                               requests into one decode)
        self.lm_stream = lm_stream  # Callable[..., Iterator[str]] | None —
        # when set, deltas stream out on events.text.generated.partial while
        # decoding; the final full message still rides events.text.generated
        # generation-session durability (resilience/genlog.py): the engine
        # APPENDS chunk snapshots; this service owns terminal mark_done —
        # recorded only AFTER the result is published, so a crash anywhere
        # in the publish window still leaves a resumable tail. lm_resume is
        # the adoption callable (LmEngine.generate_stream's signature with
        # task_id/stream/resume) driven by _handle_resume.
        self.journal = journal
        self.lm_resume = lm_resume
        self._resume_max_attempts = int(resume_max_attempts)
        self._resume_backoff_s = float(resume_backoff_s)
        self._resume_tasks: set = set()  # pending backoff republishes
        # usage metering / durability: pass tenant + task_id through to the
        # engine when the stream callable takes them (LmEngine.generate_stream
        # does; duck-typed test stubs may not — probed once here)
        self._stream_params = self._probe_params(lm_stream)
        self._resume_params = self._probe_params(lm_resume)
        self.train_on_ingest = train_on_ingest
        # online LM fine-tune (train/online.OnlineLmTrainer | None): the LM
        # analog of Markov's continuous learning — ingested text buffers
        # until the threshold, then a few optimizer steps run off the event
        # loop and the serving engine picks up the updated params
        self.lm_trainer = lm_trainer
        self._lm_train_min_chars = lm_train_min_chars
        self._lm_train_steps = lm_train_steps
        # bounded backlog: if ingest sustainedly outruns device training the
        # buffer drops OLDEST docs past this budget (counted in metrics)
        # instead of growing host memory without limit
        self._lm_buffer_max_chars = lm_buffer_max_chars
        self._lm_buffer: list = []
        self._lm_buffer_chars = 0
        self._lm_train_lock = asyncio.Lock()
        self._lm_train_task: asyncio.Task | None = None
        # in-flight generations by task_id → cancel Event (overload plane):
        # a tasks.generation.cancel for a task this replica is decoding
        # frees its batch row / closes its stream at the next chunk boundary
        self._inflight: dict = {}
        # cancels that arrived BEFORE their generation task: under overload
        # — exactly when cancellation matters — generate tasks sit bus-queued
        # behind in-flight work, so the SSE reader can vanish (and its cancel
        # arrive) while the task is still undelivered. Tombstone the id
        # (with its arrival time) so registration observes it; bounded,
        # oldest ids expire first, and a stale tombstone (past the TTL) is
        # ignored — the cancel fans out to EVERY replica, so on the ones
        # that never see the task it would otherwise lie in wait for an
        # id-reusing resubmission forever.
        self._cancelled_early: dict = {}
        self._cancelled_early_ttl_s = 60.0
        # ...but a cancel for a task that already FINISHED here must not
        # tombstone (it would silently kill a resubmission reusing the id)
        self._completed_recent: dict = {}

    @staticmethod
    def _probe_params(fn) -> frozenset:
        """Keyword params a duck-typed engine callable accepts — real
        engines take tenant/task_id/stream/resume, minimal test stubs may
        take none; probed once so per-request calls stay reflection-free."""
        if fn is None:
            return frozenset()
        import inspect

        try:
            return frozenset(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            return frozenset()

    def _journal_done(self, task_id: str) -> None:
        """Terminal journal marker — called after the task's outcome is
        PUBLISHED (or it was cancelled), never earlier: a crash between
        decode finishing and the publish must still resume on a survivor."""
        if self.journal is not None and task_id:
            self.journal.mark_done(task_id)

    async def _setup(self) -> None:
        await self._subscribe_loop(subjects.TASKS_GENERATION_TEXT,
                                   self._handle_generate,
                                   queue=subjects.QUEUE_TEXT_GENERATOR)
        # cancels fan out to EVERY replica (no queue group): only the one
        # decoding the task acts; everyone else ignores the unknown id
        await self._subscribe_loop(subjects.TASKS_GENERATION_CANCEL,
                                   self._handle_cancel)
        # orphaned-session adoption (resilience/genlog.py): the supervisor
        # republishes a dead worker's journal tails here; the queue group
        # makes exactly one survivor adopt each
        await self._subscribe_loop(subjects.TASKS_GENERATION_RESUME,
                                   self._handle_resume,
                                   queue=subjects.QUEUE_TEXT_GENERATOR)
        if self.train_on_ingest or self.lm_trainer is not None:
            # continuous learning from the pipeline (no queue group: every
            # generator replica learns the full stream)
            await self._subscribe_loop(subjects.DATA_RAW_TEXT_DISCOVERED,
                                       self._handle_train)

    async def _handle_cancel(self, msg: Msg) -> None:
        import json as _json

        try:
            task_id = _json.loads(msg.data).get("task_id")
        except (ValueError, AttributeError):
            return
        ev = self._inflight.get(task_id)
        metrics.inc("text_generator.cancel_requests")
        if ev is not None and not ev.is_set():
            ev.set()
            metrics.inc("text_generator.cancelled")
            log.info("generation %s cancelled (client disconnected)", task_id)
        elif ev is None and task_id and task_id not in self._completed_recent:
            import time as _time

            self._cancelled_early[task_id] = _time.monotonic()
            while len(self._cancelled_early) > 256:
                self._cancelled_early.pop(next(iter(self._cancelled_early)))

    async def _handle_train(self, msg: Msg) -> None:
        raw = from_json(RawTextMessage, msg.data)
        if self.train_on_ingest:
            self.markov.train(raw.raw_text)
            metrics.inc("text_generator.trained_docs")
            self._dirty = True
            await self._maybe_save()
        if self.lm_trainer is not None:
            self._lm_buffer.append(raw.raw_text)
            self._lm_buffer_chars += len(raw.raw_text)
            while (self._lm_buffer_chars > self._lm_buffer_max_chars
                   and len(self._lm_buffer) > 1):
                dropped = self._lm_buffer.pop(0)
                self._lm_buffer_chars -= len(dropped)
                metrics.inc("text_generator.lm_train_dropped_docs")
                metrics.inc("text_generator.lm_train_dropped_chars",
                            len(dropped))
            # fire-and-forget: the handler must NOT await the pass — parked
            # handler tasks would exhaust the service's handler semaphore and
            # stall every subscription (incl. generation requests) behind a
            # multi-second training pass. One background task drains the
            # buffer in a loop; docs arriving mid-pass buffer for its next
            # iteration.
            if (self._lm_buffer_chars >= self._lm_train_min_chars
                    and not self._lm_train_lock.locked()):
                self._lm_train_task = asyncio.create_task(
                    self._lm_train_pass(), name="lm-ingest-train")
                # fire-and-forget tasks swallow exceptions unless retrieved;
                # log every pass's failure the moment it happens instead of
                # staying silent until the threshold next crosses
                self._lm_train_task.add_done_callback(self._log_train_failure)

    @staticmethod
    def _log_train_failure(task: "asyncio.Task") -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            log.error("online LM fine-tune pass failed", exc_info=exc)
            metrics.inc("text_generator.lm_train_failures")

    async def _lm_train_pass(self) -> None:
        """Drain buffered ingest through fine-tune passes, off the event
        loop, until the buffer is below the threshold."""
        async with self._lm_train_lock:
            while self._lm_buffer_chars >= self._lm_train_min_chars:
                texts, self._lm_buffer, self._lm_buffer_chars = \
                    self._lm_buffer, [], 0
                with span("text_generator.lm_train", None, docs=len(texts)):
                    out = await asyncio.get_running_loop().run_in_executor(
                        None, lambda: self.lm_trainer.train_on_texts(
                            texts, steps=self._lm_train_steps))
                metrics.inc("text_generator.lm_train_passes")
                metrics.inc("text_generator.lm_train_docs", len(texts))
                loss = (float("nan") if out["loss"] is None
                        else out["loss"])  # 0.0 is a real, healthy loss
                log.info("online LM fine-tune: %d docs, %d steps, loss %.4f",
                         len(texts), out["steps"], loss)

    async def stop(self) -> None:
        await super().stop()
        await self._maybe_save(force=True)  # flush unsaved learning
        if self._lm_train_task is not None and not self._lm_train_task.done():
            # let an in-flight fine-tune pass finish (it persists its own
            # state); buffered-but-untrained text is the only loss on stop.
            # A failing pass must not abort shutdown — the done-callback
            # already logged it with traceback; just swallow here.
            try:
                await self._lm_train_task
            except Exception:
                pass

    # ------------------------------------------------- markov persistence

    def _load_state(self):
        if not self._state_path:
            return None
        import json
        from pathlib import Path

        try:
            raw = Path(self._state_path).read_text(encoding="utf-8")
        except OSError:
            return None  # first boot
        try:
            model = MarkovModel.from_state(json.loads(raw))
            log.info("markov state restored from %s (%d chain keys)",
                     self._state_path, len(model.chain))
            return model
        except Exception:
            log.exception("corrupt markov state at %s; starting fresh",
                          self._state_path)
            return None

    async def _maybe_save(self, force: bool = False) -> None:
        """Debounced persist: at most one save per window (per-doc O(chain)
        serialization would make cumulative ingest cost quadratic), JSON dump
        + file I/O in an executor so the event loop never stalls behind a
        large chain. The snapshot is copied on the loop first — the chain
        mutates between handler awaits."""
        import time

        if not self._state_path or not self._dirty:
            return
        now = time.monotonic()
        if not force and now - self._last_save < 2.0:
            return
        state = self.markov.to_state()
        snapshot = {"chain": {k: list(v) for k, v in state["chain"].items()},
                    "starters": list(state["starters"])}
        self._dirty = False
        self._last_save = now
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, self._write_state, snapshot)
        except Exception:
            # failed write (disk full, permissions): the delta is NOT saved —
            # re-mark dirty so the next window retries instead of silently
            # dropping learned state until a future ingest re-dirties it
            self._dirty = True
            log.exception("markov state save failed; will retry")

    def _write_state(self, snapshot: dict) -> None:
        import json
        import os
        from pathlib import Path

        path = Path(self._state_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(snapshot, ensure_ascii=False),
                       encoding="utf-8")
        os.replace(tmp, path)  # atomic: a crash never leaves a torn file

    async def _handle_generate(self, msg: Msg) -> None:
        task = from_json(GenerateTextTask, msg.data)
        import time as _time

        cancel = asyncio.Event()
        tombstone = self._cancelled_early.pop(task.task_id, None)
        if (tombstone is not None
                and _time.monotonic() - tombstone
                <= self._cancelled_early_ttl_s
                and task.task_id not in self._completed_recent):
            # the cancel raced ahead of the task across the two subjects:
            # honor it now or the decode runs its full budget for a reader
            # that is already gone (stale tombstones are ignored — see
            # _cancelled_early above). The recently-completed guard covers
            # the RETRY path too: a cancel landing during a failed
            # delivery's backoff tombstones, but if the task meanwhile
            # completed (this replica published its text), the redelivery
            # must be a no-op-ish rerun, not a poisoned cancel — same rule
            # _handle_cancel already applies to live tombstoning.
            cancel.set()
            metrics.inc("text_generator.cancelled")
        self._inflight[task.task_id] = cancel
        try:
            with span("text_generator.generate", msg.headers,
                      max_length=task.max_length):
                if self.lm_stream is not None and task.stream:
                    # per-request opt-in: a stream decodes chunk-by-chunk
                    # (the engine lock is released between chunks,
                    # lm.py:328-336) but still can't share one batched
                    # executable with other requests, so only explicit
                    # stream=true requests take it — everything else rides
                    # the micro-batcher
                    text = await self._stream_generate(task, msg.headers,
                                                       cancel)
                elif self.lm_batcher is not None:
                    # cancel frees the request's decode row at the next
                    # chunk boundary (GenBatcher → BatchSession.cancel_tag);
                    # the tenant header picks the fairness lane; task_id
                    # keys the row's crash-resume journal snapshots
                    text = await self.lm_batcher.generate(
                        task.prompt or "", task.max_length,
                        temperature=task.temperature, top_k=task.top_k,
                        cancel=cancel,
                        tenant=admission.tenant_of(msg.headers),
                        task_id=task.task_id)
                elif self.lm_generate is not None:
                    text = await asyncio.get_running_loop().run_in_executor(
                        None, lambda: self.lm_generate(
                            task.prompt or "", task.max_length,
                            temperature=task.temperature, top_k=task.top_k))
                else:
                    # Markov backend has no sampling knobs: temperature/top_k
                    # are accepted on the wire but ignored (documented in
                    # schema)
                    text = self.markov.generate(task.max_length)
        finally:
            self._inflight.pop(task.task_id, None)
        # completion is only recorded on the NORMAL path: a raised handler
        # will be retried (services/base.py), and a cancel landing during
        # its backoff must still tombstone so the retry aborts
        self._completed_recent[task.task_id] = True
        while len(self._completed_recent) > 256:
            self._completed_recent.pop(next(iter(self._completed_recent)))
        if text is None or cancel.is_set():
            # cancelled mid-decode: nobody is listening — no final event,
            # and the journal tail is terminal (a cancelled task must never
            # resurrect as a resume after a later worker death)
            self._journal_done(task.task_id)
            return
        out = GeneratedTextMessage(original_task_id=task.task_id,
                                   generated_text=text,
                                   timestamp_ms=current_timestamp_ms())
        await self.bus.publish(subjects.EVENTS_TEXT_GENERATED,
                               to_json_bytes(out),
                               headers=child_headers(msg.headers))
        metrics.inc("text_generator.generated")
        # mark the journal tail done only now — the result is on the bus
        self._journal_done(task.task_id)

    async def _stream_generate(self, task: GenerateTextTask, headers,
                               cancel=None, resume=None):
        """Drive the decode generator in an executor thread; every text delta
        crossing back is published as a GeneratedTextChunk before the next
        chunk even starts decoding. Returns the accumulated full text — or
        None when `cancel` was set mid-stream (the generator is CLOSED at
        the next chunk boundary, which runs its finally block and releases
        its decode state; the terminal done-chunk still goes out so any
        remaining consumer sees a clean close).

        `resume` (a journal tail record — resilience/genlog.py) switches the
        call into orphan adoption: the engine re-prefills the dead worker's
        prompt+generated prefix and replays its last journaled chunk, so
        seq numbering CONTINUES from the record (the SSE hub dedupes the
        replayed chunk by seq — exactly-once at the edge) and the returned
        full text prepends the text the dead worker already emitted.
        Partials are only published when the originating task streamed."""
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        if resume is not None:
            fn, params = self.lm_resume, self._resume_params
        else:
            fn, params = self.lm_stream, self._stream_params
        kw = {}
        if "tenant" in params:
            kw["tenant"] = (resume.get("tenant") if resume is not None
                            else admission.tenant_of(headers))
        if "task_id" in params:
            kw["task_id"] = task.task_id
        if resume is not None:
            if "stream" in params:
                kw["stream"] = bool(resume.get("stream"))
            kw["resume"] = resume

        def produce() -> None:
            gen = fn(task.prompt or "", task.max_length,
                     temperature=task.temperature,
                     top_k=task.top_k, **kw)
            try:
                for delta in gen:
                    if cancel is not None and cancel.is_set():
                        # closing the generator runs its finally (stats
                        # flushed, device state dropped) — the decode stops
                        # at this chunk instead of running out the budget
                        gen.close()
                        loop.call_soon_threadsafe(queue.put_nowait,
                                                  ("cancelled", None))
                        return
                    loop.call_soon_threadsafe(queue.put_nowait, ("delta", delta))
                loop.call_soon_threadsafe(queue.put_nowait, ("end", None))
            except BaseException as e:  # surface decode errors to the handler
                loop.call_soon_threadsafe(queue.put_nowait, ("error", e))

        producer = loop.run_in_executor(None, produce)
        parts: list = []
        seq = int(resume.get("seq") or 0) if resume is not None else 0
        publish_partials = (resume is None) or bool(resume.get("stream"))
        cancelled = False
        suppress_close = False
        try:
            while True:
                kind, payload = await queue.get()
                if kind == "delta":
                    parts.append(payload)
                    if publish_partials:
                        await self.bus.publish(
                            subjects.EVENTS_TEXT_GENERATED_PARTIAL,
                            to_json_bytes(GeneratedTextChunk(
                                original_task_id=task.task_id,
                                text_delta=payload,
                                seq=seq, done=False,
                                timestamp_ms=current_timestamp_ms())),
                            headers=child_headers(headers))
                        metrics.inc("text_generator.stream_chunks")
                    seq += 1
                elif kind == "end":
                    break
                elif kind == "cancelled":
                    cancelled = True
                    break
                else:
                    if resume is not None and isinstance(payload,
                                                        PoolExhausted):
                        # transient admission refusal: the stream is NOT
                        # over — the requeued resume continues it; a done
                        # chunk here would close the waiting client early
                        suppress_close = True
                    raise payload
        finally:
            await producer
            if publish_partials and not suppress_close:
                # terminal chunk ALWAYS goes out — on a decode error too, so
                # stream consumers get a close signal instead of hanging
                await self.bus.publish(
                    subjects.EVENTS_TEXT_GENERATED_PARTIAL,
                    to_json_bytes(GeneratedTextChunk(
                        original_task_id=task.task_id, text_delta="", seq=seq,
                        done=True, timestamp_ms=current_timestamp_ms())),
                    headers=child_headers(headers))
        if cancelled:
            return None
        prefix = (resume.get("text") or "") if resume is not None else ""
        return prefix + "".join(parts)

    # ------------------------------------------ orphaned-session adoption

    async def _handle_resume(self, msg: Msg) -> None:
        """Adopt one orphaned generation session (docs/RESILIENCE.md
        "Durable generation sessions"): the supervisor republished a dead
        worker's journal tail as {"task_id", "record", "attempt"}. The
        engine re-prefills the journaled prompt+generated prefix and
        continues the stream with monotonic seq; the SSE hub dedupes the
        one replayed chunk — the client-observed token sequence stays
        exactly-once and (greedy) token-identical to an unkilled run."""
        import json as _json
        import time as _time

        try:
            payload = _json.loads(msg.data)
        except (ValueError, AttributeError):
            return
        rec = payload.get("record") or {}
        task_id = payload.get("task_id") or rec.get("task_id")
        attempt = int(payload.get("attempt") or 0)
        if not task_id:
            return
        if self.lm_resume is None or not rec.get("prompt_ids"):
            # no adoption-capable engine in this replica / torn record:
            # counted loudly — this is the stream staying lost
            metrics.inc("gen.resume_abandoned")
            log.warning("cannot adopt orphaned generation %s "
                        "(engine=%s, record ok=%s)", task_id,
                        self.lm_resume is not None,
                        bool(rec.get("prompt_ids")))
            return
        # resume-races-cancel: the client hung up before the worker died —
        # its cancel fanned out to every replica and tombstoned the id here.
        # Honor the tombstone: drop the resume instead of decoding for a
        # reader that is gone.
        tombstone = self._cancelled_early.pop(task_id, None)
        if (tombstone is not None
                and _time.monotonic() - tombstone
                <= self._cancelled_early_ttl_s):
            metrics.inc("gen.resume_dropped_cancelled")
            log.info("dropping resume for cancelled generation %s", task_id)
            return
        if task_id in self._completed_recent:
            # this replica already published the task's text (the orphan
            # was a journal tail whose done-marker died with the worker)
            metrics.inc("gen.resume_dropped_completed")
            return
        task = GenerateTextTask(
            task_id=task_id, prompt="",
            max_length=int(rec.get("max_new") or 1),
            stream=bool(rec.get("stream")),
            temperature=rec.get("temperature"), top_k=rec.get("top_k"))
        cancel = asyncio.Event()
        self._inflight[task_id] = cancel
        try:
            with span("text_generator.resume", msg.headers,
                      attempt=attempt, tokens=len(rec.get("tokens") or ())):
                text = await self._stream_generate(task, msg.headers,
                                                   cancel, resume=rec)
        except PoolExhausted:
            # resume-under-pressure: the adopting engine refused admission
            # (no KV headroom). Re-queue bounded-with-backoff — the orphan
            # outlives a pressure spike instead of dying to it.
            await self._requeue_resume(task_id, rec, attempt)
            return
        finally:
            self._inflight.pop(task_id, None)
        self._completed_recent[task_id] = True
        while len(self._completed_recent) > 256:
            self._completed_recent.pop(next(iter(self._completed_recent)))
        if text is None or cancel.is_set():
            self._journal_done(task_id)
            return
        await self.bus.publish(
            subjects.EVENTS_TEXT_GENERATED,
            to_json_bytes(GeneratedTextMessage(
                original_task_id=task_id, generated_text=text,
                timestamp_ms=current_timestamp_ms())),
            headers=child_headers(msg.headers))
        metrics.inc("text_generator.generated")
        self._journal_done(task_id)

    async def _requeue_resume(self, task_id: str, rec: dict,
                              attempt: int) -> None:
        """Bounded exponential-backoff republish of a pressure-refused
        resume. Fire-and-forget sleep task: parking the handler itself
        would eat a handler-semaphore slot for the whole backoff."""
        import json as _json

        if attempt + 1 >= self._resume_max_attempts:
            metrics.inc("gen.resume_abandoned")
            log.warning("orphaned generation %s abandoned after %d "
                        "pressure-refused resume attempts", task_id,
                        attempt + 1)
            return
        metrics.inc("gen.resume_requeued")
        delay = self._resume_backoff_s * (2 ** attempt)
        body = _json.dumps({"task_id": task_id, "record": rec,
                            "attempt": attempt + 1}).encode()

        async def later() -> None:
            await asyncio.sleep(delay)
            await self.bus.publish(subjects.TASKS_GENERATION_RESUME, body)

        t = asyncio.create_task(later(), name=f"gen-resume-requeue-{task_id}")
        self._resume_tasks.add(t)
        t.add_done_callback(self._resume_tasks.discard)
