"""Text-generator service.

Parity with reference: services/text_generator_service/src/main.rs:111-162:
consumes GenerateTextTask, generates, publishes GeneratedTextMessage to
events.text.generated. Two backends:

- Markov (default, reference parity) — but trained continuously on every
  ingested document (the reference trains once on one hardcoded sentence and
  ignores the prompt, main.rs:120-123,169-174);
- TPU LM (optional, BASELINE.md config #5): decoder LM via models/gpt with
  the prompt actually used.
"""

from __future__ import annotations

import asyncio
import logging

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import Msg
from symbiont_tpu.models.markov import MarkovModel
from symbiont_tpu.schema import (
    GeneratedTextChunk,
    GeneratedTextMessage,
    GenerateTextTask,
    RawTextMessage,
    from_json,
    to_json_bytes,
)
from symbiont_tpu.services.base import Service
from symbiont_tpu.utils.ids import current_timestamp_ms
from symbiont_tpu.utils.telemetry import child_headers, metrics, span

log = logging.getLogger(__name__)

# the reference's single hardcoded training sentence (main.rs:170) — kept as
# the cold-start corpus so an empty system still generates
SEED_CORPUS = (
    "Это первое предложение для обучения нашей марковской модели оно простое"
)


class TextGeneratorService(Service):
    name = "text_generator"

    def __init__(self, bus, lm_generate=None, lm_batcher=None, lm_stream=None,
                 train_on_ingest: bool = True):
        super().__init__(bus)
        self.markov = MarkovModel()
        self.markov.train(SEED_CORPUS)
        self.lm_generate = lm_generate  # Callable[[str, int], str] | None
        self.lm_batcher = lm_batcher  # GenBatcher | None (batches concurrent
        #                               requests into one decode)
        self.lm_stream = lm_stream  # Callable[..., Iterator[str]] | None —
        # when set, deltas stream out on events.text.generated.partial while
        # decoding; the final full message still rides events.text.generated
        self.train_on_ingest = train_on_ingest

    async def _setup(self) -> None:
        await self._subscribe_loop(subjects.TASKS_GENERATION_TEXT,
                                   self._handle_generate,
                                   queue=subjects.QUEUE_TEXT_GENERATOR)
        if self.train_on_ingest:
            # continuous learning from the pipeline (no queue group: every
            # generator replica learns the full stream)
            await self._subscribe_loop(subjects.DATA_RAW_TEXT_DISCOVERED,
                                       self._handle_train)

    async def _handle_train(self, msg: Msg) -> None:
        raw = from_json(RawTextMessage, msg.data)
        self.markov.train(raw.raw_text)
        metrics.inc("text_generator.trained_docs")

    async def _handle_generate(self, msg: Msg) -> None:
        task = from_json(GenerateTextTask, msg.data)
        with span("text_generator.generate", msg.headers,
                  max_length=task.max_length):
            if self.lm_stream is not None and task.stream:
                # per-request opt-in: streaming holds the engine for the
                # whole decode, so only explicit stream=true requests take
                # it — everything else rides the micro-batcher
                text = await self._stream_generate(task, msg.headers)
            elif self.lm_batcher is not None:
                text = await self.lm_batcher.generate(task.prompt or "",
                                                      task.max_length)
            elif self.lm_generate is not None:
                text = await asyncio.get_running_loop().run_in_executor(
                    None, self.lm_generate, task.prompt or "", task.max_length)
            else:
                text = self.markov.generate(task.max_length)
        out = GeneratedTextMessage(original_task_id=task.task_id,
                                   generated_text=text,
                                   timestamp_ms=current_timestamp_ms())
        await self.bus.publish(subjects.EVENTS_TEXT_GENERATED,
                               to_json_bytes(out),
                               headers=child_headers(msg.headers))
        metrics.inc("text_generator.generated")

    async def _stream_generate(self, task: GenerateTextTask, headers) -> str:
        """Drive the decode generator in an executor thread; every text delta
        crossing back is published as a GeneratedTextChunk before the next
        chunk even starts decoding. Returns the accumulated full text."""
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        def produce() -> None:
            try:
                for delta in self.lm_stream(task.prompt or "",
                                            task.max_length):
                    loop.call_soon_threadsafe(queue.put_nowait, ("delta", delta))
                loop.call_soon_threadsafe(queue.put_nowait, ("end", None))
            except BaseException as e:  # surface decode errors to the handler
                loop.call_soon_threadsafe(queue.put_nowait, ("error", e))

        producer = loop.run_in_executor(None, produce)
        parts: list = []
        seq = 0
        try:
            while True:
                kind, payload = await queue.get()
                if kind == "delta":
                    parts.append(payload)
                    await self.bus.publish(
                        subjects.EVENTS_TEXT_GENERATED_PARTIAL,
                        to_json_bytes(GeneratedTextChunk(
                            original_task_id=task.task_id, text_delta=payload,
                            seq=seq, done=False,
                            timestamp_ms=current_timestamp_ms())),
                        headers=child_headers(headers))
                    seq += 1
                    metrics.inc("text_generator.stream_chunks")
                elif kind == "end":
                    break
                else:
                    raise payload
        finally:
            await producer
            # terminal chunk ALWAYS goes out — on a decode error too, so
            # stream consumers get a close signal instead of hanging forever
            await self.bus.publish(
                subjects.EVENTS_TEXT_GENERATED_PARTIAL,
                to_json_bytes(GeneratedTextChunk(
                    original_task_id=task.task_id, text_delta="", seq=seq,
                    done=True, timestamp_ms=current_timestamp_ms())),
                headers=child_headers(headers))
        return "".join(parts)
