"""HTML main-content extraction — parity with the reference's scraper cascade.

Reference (services/perception_service/src/main.rs:86-170):
1. find the first element matching, in order: article, main, div[role='main'],
   div.content, div.post-content, div.entry-content, body — else whole doc;
2. within it, for each of h1..h6, p, li, span in that order, collect each
   element's text nodes (trimmed, space-joined), skipping empties;
3. join parts with newlines, trim lines, drop empty lines.

Implemented on the stdlib html.parser (no external scraper dependency): a tiny
DOM with just enough selector support for the cascade above.
"""

from __future__ import annotations

from html.parser import HTMLParser
from typing import List, Optional

VOID_ELEMENTS = {
    "area", "base", "br", "col", "embed", "hr", "img", "input", "link",
    "meta", "param", "source", "track", "wbr",
}

SKIP_TEXT_IN = {"script", "style", "noscript", "template"}

CONTENT_SELECTORS = [
    "article", "main", "div[role='main']", "div.content",
    "div.post-content", "div.entry-content", "body",
]

TEXT_SELECTORS = ["h1", "h2", "h3", "h4", "h5", "h6", "p", "li", "span"]


class Node:
    __slots__ = ("tag", "attrs", "children", "parent")

    def __init__(self, tag: str, attrs: Optional[dict] = None, parent=None):
        self.tag = tag
        self.attrs = attrs or {}
        self.children: list = []  # Node or str (text)
        self.parent = parent


class _DomBuilder(HTMLParser):
    def __init__(self) -> None:
        super().__init__(convert_charrefs=True)
        self.root = Node("#document")
        self.stack = [self.root]

    def handle_starttag(self, tag, attrs):
        node = Node(tag, dict(attrs), parent=self.stack[-1])
        self.stack[-1].children.append(node)
        if tag not in VOID_ELEMENTS:
            self.stack.append(node)

    def handle_startendtag(self, tag, attrs):
        self.stack[-1].children.append(Node(tag, dict(attrs), parent=self.stack[-1]))

    def handle_endtag(self, tag):
        # close the nearest matching open tag (tolerant of malformed HTML)
        for i in range(len(self.stack) - 1, 0, -1):
            if self.stack[i].tag == tag:
                del self.stack[i:]
                break

    def handle_data(self, data):
        if data:
            self.stack[-1].children.append(data)


def parse_html(html: str) -> Node:
    b = _DomBuilder()
    b.feed(html)
    b.close()
    return b.root


def _matches(node: Node, selector: str) -> bool:
    if "[" in selector:  # tag[attr='value']
        tag, rest = selector.split("[", 1)
        attr, value = rest.rstrip("]").split("=", 1)
        value = value.strip("'\"")
        return node.tag == tag and node.attrs.get(attr) == value
    if "." in selector:  # tag.class
        tag, cls = selector.split(".", 1)
        classes = (node.attrs.get("class") or "").split()
        return node.tag == tag and cls in classes
    return node.tag == selector


def _walk(node: Node):
    for child in node.children:
        if isinstance(child, Node):
            yield child
            yield from _walk(child)


def find_first(root: Node, selector: str) -> Optional[Node]:
    for node in _walk(root):
        if _matches(node, selector):
            return node
    return None


def select_all(root: Node, selector: str) -> List[Node]:
    return [n for n in _walk(root) if _matches(n, selector)]


def _text_nodes(node: Node):
    if node.tag in SKIP_TEXT_IN:
        return
    for child in node.children:
        if isinstance(child, str):
            yield child
        else:
            yield from _text_nodes(child)


def element_text(node: Node) -> str:
    """Trimmed text nodes joined with single spaces (main.rs:133-142)."""
    parts = [t.strip() for t in _text_nodes(node)]
    return " ".join(p for p in parts if p)


def extract_main_text(html: str) -> str:
    """Full cascade (main.rs:100-160)."""
    doc = parse_html(html)
    scope = None
    for sel in CONTENT_SELECTORS:
        scope = find_first(doc, sel)
        if scope is not None:
            break
    if scope is None:
        scope = doc
    parts: List[str] = []
    for sel in TEXT_SELECTORS:
        for el in select_all(scope, sel):
            text = element_text(el)
            if text:
                parts.append(text)
    lines = [ln.strip() for ln in "\n".join(parts).split("\n")]
    return "\n".join(ln for ln in lines if ln)
