"""Cross-message upsert coalescing — the ingest write path off lockstep.

ROADMAP item 3 (the 5× host gap): after the tensor-frame plane removed
per-float serialization, the Python ingest path still paid one
`upsert_rows` store call — a WAL fsync + lock round-trip — per
`data.text.with_embeddings` message (~25 rows). The bulk-ingest tier
amortizes that cost over 10k rows in one call; the live pipeline should
too. `UpsertCoalescer` accumulates the rows of MANY messages and lands
them as one store call, flushing when `max_rows` is reached, when the
oldest pending row has waited `max_age_ms`, or at shutdown.

The ack contract (docs/RESILIENCE.md failure-mode matrix): each message's
`add()` future resolves only when the flush carrying ITS rows has
committed — the service handler awaits it, so the durable delivery is
acked strictly AFTER the store write (or its breaker/WAL spill, which
`ResilientVectorStore` reports as success by design: the spill IS durable).
A crashed flush sets the exception on every waiter in that flush; their
handlers fail, their deliveries stay unacked, and redelivery re-coalesces
them — the deterministic point ids make the retry idempotent, so at-least
-once coalescing never duplicates points (proven by tests/test_coalesce.py
and the chaos suite).

Entries are grouped by embedding dim at flush time: a poison message whose
frame dim mismatches the store fails alone instead of dead-lettering the
healthy messages batched with it (same stance as the native vector_memory
shell's solo-retry).

`store_executor()` is the module's second export: a small dedicated
ThreadPoolExecutor for blocking store calls. Upserts/searches used to ride
the event loop's DEFAULT executor, where a slow WAL fsync competed with
embed forwards and tokenization for the same threads — the ingest stages
serialized on the pool exactly when the pipeline was busiest.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from symbiont_tpu.utils.telemetry import metrics, span

log = logging.getLogger(__name__)

_store_pool: Optional[ThreadPoolExecutor] = None
_store_pool_lock = threading.Lock()


def store_executor() -> ThreadPoolExecutor:
    """Process-shared bounded pool for blocking store WRITES (coalesced
    flushes, upserts). Separate from the default loop executor so a
    blocking WAL fsync can never starve the embed/tokenize stages of
    threads. Reads (search/count) deliberately stay on the default pool —
    they are the latency path and must not queue behind a bulk flush
    holding one of these two workers."""
    global _store_pool
    with _store_pool_lock:
        if _store_pool is None:
            _store_pool = ThreadPoolExecutor(max_workers=2,
                                             thread_name_prefix="store")
        return _store_pool


def upsert_rows_or_points(store, ids, rows, payloads) -> int:
    """One packed block into the store: the fast `upsert_rows` surface when
    the backend has it (embedded store, resilient wrapper), the point-tuple
    surface otherwise (bare external Qdrant) — the zero-copy row views pass
    through either way. Shared by every coalescer flush_fn so both
    coalescer users keep identical store semantics."""
    if hasattr(store, "upsert_rows"):
        return store.upsert_rows(ids, rows, payloads)
    return store.upsert(list(zip(ids, rows, payloads)))


@dataclass
class _PendingUpsert:
    ids: List[str]
    rows: np.ndarray  # [n, dim] f32 (zero-copy frame view or converted)
    payloads: List[dict]
    headers: Optional[dict]
    future: asyncio.Future = field(repr=False)


class UpsertCoalescer:
    """Accumulate (ids, rows, payloads) from many messages into one store
    call. `flush_fn(ids, rows, payloads) -> int` runs on the store
    executor; one flush is in flight at a time (the store serializes writes
    under its own lock anyway, and a single-writer flush keeps the ack
    bookkeeping exact)."""

    def __init__(self, flush_fn: Callable, *, max_rows: int = 512,
                 max_age_ms: float = 25.0, name: str = "vector_memory"):
        if max_rows < 1:
            raise ValueError("coalesce max_rows must be >= 1")
        if max_age_ms <= 0:
            raise ValueError("coalesce max_age_ms must be positive")
        self._flush_fn = flush_fn
        self.max_rows = max_rows
        self.max_age_s = max_age_ms / 1000.0
        self.name = name
        self._pending: List[_PendingUpsert] = []
        self._pending_rows = 0
        self._oldest_t = 0.0
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self._draining = False
        self._labels = {"service": name}

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(),
                                             name=f"{self.name}-coalescer")
            metrics.register_weakref_gauge(
                "coalesce.pending_rows", self,
                lambda c: None if c._closed else c._pending_rows,
                labels=self._labels)

    def drain_mode(self) -> None:
        """Drain protocol (resilience/autoscale.py scale-in): from now on
        every pending batch flushes IMMEDIATELY — the age window is
        skipped, so in-flight handlers' ack-waits resolve without waiting
        out `max_age_ms`, and `Service.drain()`'s wait-for-handlers can
        never deadlock behind a long window. New `add()`s still work (a
        handler mid-flight may add after this flips); they flush on the
        next cycle."""
        self._draining = True
        self._wake.set()

    async def stop(self) -> None:
        """Flush-on-stop: everything pending lands (and its acks release)
        before the loop dies — shutdown is a flush trigger, never a drop."""
        self._closed = True
        self._wake.set()
        if self._task is not None:
            await self._task
            self._task = None
        if self._pending:  # the loop exited before a late add (tests)
            await self._flush("stop")

    async def add(self, ids: Sequence[str], rows, payloads: Sequence[dict],
                  headers: Optional[dict] = None) -> int:
        """Queue one message's rows; resolves with its row count once the
        flush carrying them has committed. Raises what the flush raised —
        the caller's handler then fails and the delivery stays unacked."""
        if self._closed:
            raise RuntimeError("coalescer closed")
        arr = np.asarray(rows, dtype=np.float32)
        if arr.ndim != 2 or arr.shape[0] != len(ids):
            raise ValueError(
                f"rows shape {arr.shape} does not match {len(ids)} ids")
        if len(payloads) != len(ids):
            raise ValueError(f"{len(payloads)} payloads for {len(ids)} ids")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if not self._pending:
            self._oldest_t = time.monotonic()
        self._pending.append(_PendingUpsert(list(ids), arr, list(payloads),
                                            headers, fut))
        self._pending_rows += arr.shape[0]
        metrics.inc("coalesce.messages", labels=self._labels)
        metrics.inc("coalesce.rows", arr.shape[0], labels=self._labels)
        self._wake.set()
        return await fut

    # ------------------------------------------------------------ internals

    async def _run(self) -> None:
        while True:
            if not self._pending:
                if self._closed:
                    return
                self._wake.clear()
                await self._wake.wait()
                continue
            if (self._pending_rows < self.max_rows and not self._closed
                    and not self._draining):
                # age window: give the next messages a chance to batch up
                wait = self._oldest_t + self.max_age_s - time.monotonic()
                if wait > 0:
                    try:
                        await asyncio.wait_for(self._sleep_until_full(), wait)
                    except asyncio.TimeoutError:
                        pass
            trigger = ("stop" if self._closed
                       else "drain" if self._draining
                       else "rows" if self._pending_rows >= self.max_rows
                       else "age")
            await self._flush(trigger)

    async def _sleep_until_full(self) -> None:
        while (self._pending_rows < self.max_rows and not self._closed
               and not self._draining):
            self._wake.clear()
            await self._wake.wait()

    async def _flush(self, trigger: str) -> None:
        batch, self._pending = self._pending, []
        self._pending_rows = 0
        if not batch:
            return
        # dim groups flush separately: a poison dim fails only its own group
        groups: Dict[int, List[_PendingUpsert]] = {}
        for p in batch:
            groups.setdefault(int(p.rows.shape[1]), []).append(p)
        loop = asyncio.get_running_loop()
        for group in groups.values():
            ids: List[str] = []
            payloads: List[dict] = []
            for p in group:
                ids.extend(p.ids)
                payloads.extend(p.payloads)
            # per GROUP, not per cycle: each group is its own store call,
            # so `coalesce.flushes` counts store calls and `flush_rows` is
            # the real rows-per-call amortization factor
            metrics.inc("coalesce.flushes", labels={**self._labels,
                                                    "trigger": trigger})
            metrics.observe("coalesce.flush_rows", len(ids),
                            labels=self._labels)
            rows = (group[0].rows if len(group) == 1
                    else np.concatenate([p.rows for p in group], axis=0))
            try:
                # the span rides the FIRST message's trace context: one
                # ingest trace per flush shows the real store write it
                # shared (the other messages' handler spans cover their
                # ack-wait on this same flush)
                with span(f"{self.name}.flush", group[0].headers,
                          rows=len(ids), messages=len(group)):
                    await loop.run_in_executor(
                        store_executor(), self._flush_fn, ids, rows, payloads)
            except Exception as e:
                log.exception("%s: coalesced flush of %d rows from %d "
                              "messages failed", self.name, len(ids),
                              len(group))
                metrics.inc("coalesce.flush_failures", labels=self._labels)
                for p in group:
                    if not p.future.done():
                        p.future.set_exception(e)
                continue
            for p in group:
                if not p.future.done():
                    p.future.set_result(len(p.ids))
