"""ID + timestamp helpers.

Parity with the reference's two shared helpers
(reference: libs/shared_models/src/lib.rs:112-121).
"""

from __future__ import annotations

import time
import uuid


def current_timestamp_ms() -> int:
    """Milliseconds since the Unix epoch (u64 semantics in the wire schema)."""
    return int(time.time() * 1000)


def generate_uuid() -> str:
    """Random UUIDv4 string, the id format used on every wire message."""
    return str(uuid.uuid4())


_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def _fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def deterministic_point_id(doc_id: str, order: int) -> str:
    """Deterministic UUID-shaped id for a (document, sentence_order) pair.

    The reference mints a random uuid per point per upsert attempt
    (reference: services/vector_memory_service/src/main.rs:142-177), which is
    fine at-most-once but duplicates points when a durable stream redelivers
    an embeddings message whose ack was lost. A content-derived id makes the
    upsert idempotent: the retry overwrites the same point. Implemented
    identically in C++ (native/services/common.hpp) so mixed-language workers
    in one queue group converge on the same ids.
    """
    key = f"{doc_id}\x00{order}".encode()
    hi = _fnv1a64(key)
    lo = _fnv1a64(key + b"\x01")
    hi = (hi & 0xFFFFFFFFFFFF0FFF) | 0x0000000000005000  # version 5 nibble
    lo = (lo & 0x3FFFFFFFFFFFFFFF) | 0x8000000000000000  # variant 10
    return (f"{hi >> 32:08x}-{(hi >> 16) & 0xFFFF:04x}-{hi & 0xFFFF:04x}-"
            f"{lo >> 48:04x}-{(lo >> 32) & 0xFFFF:04x}{lo & 0xFFFFFFFF:08x}")
