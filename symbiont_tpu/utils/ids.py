"""ID + timestamp helpers.

Parity with the reference's two shared helpers
(reference: libs/shared_models/src/lib.rs:112-121).
"""

from __future__ import annotations

import time
import uuid


def current_timestamp_ms() -> int:
    """Milliseconds since the Unix epoch (u64 semantics in the wire schema)."""
    return int(time.time() * 1000)


def generate_uuid() -> str:
    """Random UUIDv4 string, the id format used on every wire message."""
    return str(uuid.uuid4())
