"""Startup connect-retry shared by the external store adapters.

Mirrors the reference's connect-at-startup retry loops (Qdrant 5×5s:
reference vector_memory_service/src/main.rs:505-532; Neo4j 5×3s:
knowledge_graph_service/src/main.rs:253-284): warn per attempt, sleep only
BETWEEN attempts, raise ConnectionError with the last cause when exhausted.
Exceptions listed in `fatal` (config errors like a dim mismatch) propagate
immediately — retrying can't fix them.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Tuple, Type, TypeVar

log = logging.getLogger(__name__)

T = TypeVar("T")


def connect_retry(fn: Callable[[], T], *, retries: int, delay_s: float,
                  what: str,
                  fatal: Tuple[Type[BaseException], ...] = ()) -> T:
    last: Exception | None = None
    for attempt in range(retries):
        try:
            return fn()
        except fatal:
            raise
        except Exception as e:
            last = e
            log.warning("%s not ready (attempt %d/%d): %s",
                        what, attempt + 1, retries, e)
            if attempt + 1 < retries:
                time.sleep(delay_s)
    raise ConnectionError(f"{what} unreachable: {last}")
