"""Connect-retry shared by the external store adapters.

Mirrors the reference's connect-at-startup retry loops (Qdrant 5×5s:
reference vector_memory_service/src/main.rs:505-532; Neo4j 5×3s:
knowledge_graph_service/src/main.rs:253-284): warn per attempt, sleep only
BETWEEN attempts, raise ConnectionError with the last cause when exhausted.
Exceptions listed in `fatal` (config errors like a dim mismatch) propagate
immediately — retrying can't fix them.

Resilience-plane additions:
- `jitter`: full-jitter on the between-attempt sleep (uniform in
  [delay/2, delay]) so a fleet of workers restarting against one recovering
  backend doesn't reconnect in lockstep;
- `connect_retry_async`: the same loop with `asyncio.sleep`, for callers
  already on the event loop — the blocking variant smuggled `time.sleep`
  through executor threads, pinning a pool slot per retry window.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Awaitable, Callable, Optional, Tuple, Type, TypeVar

log = logging.getLogger(__name__)

T = TypeVar("T")


def jittered(delay_s: float, rng: Optional[random.Random] = None) -> float:
    """Full-jitter backoff: uniform in [delay_s/2, delay_s] — concurrent
    retriers (handler retries, loop supervisors, TCP redials, store
    reconnects) must not stampede a recovering backend in lockstep. The
    ONE definition every backoff in the tree uses."""
    r = rng.random() if rng is not None else random.random()
    return delay_s * (0.5 + 0.5 * r)


def _sleep_for(delay_s: float, jitter: bool,
               rng: Optional[random.Random]) -> float:
    return jittered(delay_s, rng) if jitter else delay_s


def connect_retry(fn: Callable[[], T], *, retries: int, delay_s: float,
                  what: str,
                  fatal: Tuple[Type[BaseException], ...] = (),
                  jitter: bool = False,
                  rng: Optional[random.Random] = None) -> T:
    last: Exception | None = None
    for attempt in range(retries):
        try:
            return fn()
        except fatal:
            raise
        except Exception as e:
            last = e
            log.warning("%s not ready (attempt %d/%d): %s",
                        what, attempt + 1, retries, e)
            if attempt + 1 < retries:
                time.sleep(_sleep_for(delay_s, jitter, rng))
    raise ConnectionError(f"{what} unreachable: {last}")


async def connect_retry_async(fn: Callable[[], Awaitable[T]], *,
                              retries: int, delay_s: float, what: str,
                              fatal: Tuple[Type[BaseException], ...] = (),
                              jitter: bool = False,
                              rng: Optional[random.Random] = None) -> T:
    """Async twin of connect_retry: `fn` is a coroutine factory; sleeps ride
    the event loop instead of blocking an executor thread."""
    last: Exception | None = None
    for attempt in range(retries):
        try:
            return await fn()
        except fatal:
            raise
        except asyncio.CancelledError:
            raise
        except Exception as e:
            last = e
            log.warning("%s not ready (attempt %d/%d): %s",
                        what, attempt + 1, retries, e)
            if attempt + 1 < retries:
                await asyncio.sleep(_sleep_for(delay_s, jitter, rng))
    raise ConnectionError(f"{what} unreachable: {last}")
