"""Tracing + metrics — the observability layer the reference lacks.

Reference state (SURVEY.md §5.1/§5.5): bare env_logger lines with bracket tags,
ids carried only inside payloads, NATS monitoring port exposed but unscraped,
zero counters. Here:

- Trace: every message carries trace/span ids in bus headers
  (X-Trace-Id/X-Span-Id); `child_headers` propagates across hops; `span`
  times a handler, logs a structured line, AND appends a SpanRecord to the
  process-global flight recorder (obs/trace_store.py) so
  `GET /api/traces/<id>` can reassemble the full pipeline tree.
- Metrics: process-global registry of counters, histograms (p50/p95/p99 +
  exact running min/max), and gauges (set/add, plus callback gauges read at
  scrape time). All three kinds take optional `{label: value}` labels —
  rendered as JSON (api /api/metrics) and as Prometheus text exposition
  (api /metrics, obs/prometheus.py).

Span-id semantics (the contract the trace tree depends on): the X-Span-Id
header names the ACTIVE span — the one under which a message was published.
`span()` mints its own id with the header's id as parent and exposes its own
context at `handle.headers`; `child_headers` PROPAGATES the active context
unchanged (a bus hop is an edge, not a span). The service base loop hands
each handler a message rebound to the handler span's context, so every
downstream publish links to it (services/base.py).
"""

from __future__ import annotations

import bisect
import json
import logging
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional, Tuple

from symbiont_tpu.obs.trace_store import SpanRecord, trace_store
from symbiont_tpu.utils.ids import generate_uuid

log = logging.getLogger("symbiont.trace")

TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"
# Overload-protection plane (resilience/admission.py): the request deadline
# (absolute unix epoch MILLISECONDS, minted at the API edge) and the tenant
# identity ride the same bus-header channel as the trace context, and
# child_headers threads them across every hop — a downstream service drops
# expired work BEFORE its handler runs (services/base.py).
DEADLINE_HEADER = "X-Symbiont-Deadline"
TENANT_HEADER = "X-Symbiont-Tenant"

# headers child_headers carries verbatim beyond the trace pair
_THREADED_HEADERS = (DEADLINE_HEADER, TENANT_HEADER)


def new_trace_headers() -> Dict[str, str]:
    return {TRACE_HEADER: generate_uuid(), SPAN_HEADER: generate_uuid()}


def child_headers(parent: Optional[Dict[str, str]]) -> Dict[str, str]:
    """Propagate the active trace context; start a new trace without one.

    The span id is carried over VERBATIM (it names the publishing span):
    the receiving handler's span records it as parent_id, which is what
    links hops into one tree. (Pre-obs versions minted a fresh span id per
    hop — an id that no recorded span owned, so trees could never link.)

    Deadline/tenant headers (the admission plane's channel) thread through
    verbatim too: a deadline minted at the API edge must reach the LAST hop
    of the pipeline, or expired work is only droppable at the first."""
    if not parent or TRACE_HEADER not in parent:
        out = new_trace_headers()
    else:
        out = {TRACE_HEADER: parent[TRACE_HEADER]}
        if SPAN_HEADER in parent:
            out[SPAN_HEADER] = parent[SPAN_HEADER]
    if parent:
        for h in _THREADED_HEADERS:
            if h in parent:
                out[h] = parent[h]
    return out


_profile_lock = threading.Lock()

# flight-recorder trace id for skipped-profile markers (obs/device.py owns
# the compile-event twin; duplicated as a literal here to keep this module
# importable below the whole obs layer)
_PROFILE_TRACE_ID = "profiler"


@contextmanager
def maybe_profile(name: str):
    """Device-level profiling hook (SURVEY.md §5.1 plan: "JAX profiler around
    the embed/decode steps"). When SYMBIONT_PROFILE_DIR is set, the wrapped
    compute runs under `jax.profiler.trace` and the XPlane trace lands there
    (view with TensorBoard's profile plugin / xprof). Off (the default) this
    is a no-op with zero per-call cost beyond one env lookup.

    Intended use: operator sets the env var on the engine process for a short
    diagnosis window; every embed / rerank / decode call in that window
    produces a trace annotated with `name`.

    The JAX profiler is process-global and non-reentrant ("Only one profile
    may be run at a time"); embed / rerank / generate can overlap across
    threads, so a call that finds a profile already running proceeds
    unprofiled rather than crashing the live request — but no longer
    SILENTLY: `profile.skipped{name=}` increments and a `profile.skipped`
    span lands in the flight recorder (trace id "profiler"), so an operator
    reading the XPlane output can tell which calls of the window it is
    missing."""
    import os

    d = os.environ.get("SYMBIONT_PROFILE_DIR")
    if not d:
        yield
        return
    if not _profile_lock.acquire(blocking=False):
        metrics.inc("profile.skipped", labels={"name": name})
        t0 = time.perf_counter()
        start_s = time.time()
        try:
            yield
        finally:
            trace_store.record(SpanRecord(
                trace_id=_PROFILE_TRACE_ID, span_id=generate_uuid(),
                parent_id=None, name="profile.skipped", start_s=start_s,
                duration_ms=(time.perf_counter() - t0) * 1000.0,
                status="ok", fields={"target": name}))
        return
    try:
        import jax

        metrics.inc("profile.captured", labels={"name": name})
        with jax.profiler.trace(d):
            with jax.profiler.TraceAnnotation(name):
                yield
    finally:
        _profile_lock.release()


class SpanHandle:
    """Live-span context yielded by `span()`. `headers` is the context to
    publish downstream messages under (same trace, THIS span as the active
    id); `fields` may be extended while the span is open and lands on the
    flight-recorder record."""

    __slots__ = ("trace_id", "span_id", "parent_id", "fields")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], fields: dict):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.fields = fields

    @property
    def headers(self) -> Dict[str, str]:
        return {TRACE_HEADER: self.trace_id, SPAN_HEADER: self.span_id}


@contextmanager
def span(name: str, headers: Optional[Dict[str, str]] = None, **fields):
    """Timed span: structured log line + `span.<name>.ms` histogram + a
    SpanRecord in the flight recorder. Errors are accounted, not swallowed:
    status lands on the record (queryable via /api/traces) and
    `span.<name>.errors` increments before the exception propagates."""
    t0 = time.perf_counter()
    start_s = time.time()
    ctx = headers or {}
    trace_id = ctx.get(TRACE_HEADER) or generate_uuid()
    handle = SpanHandle(trace_id, generate_uuid(), ctx.get(SPAN_HEADER),
                        dict(fields))
    status = "ok"
    try:
        yield handle
    except BaseException as e:
        status = "error"
        handle.fields.setdefault("error", type(e).__name__)
        metrics.inc(f"span.{name}.errors")
        raise
    finally:
        dur_ms = (time.perf_counter() - t0) * 1000
        # the trace id rides along as an exemplar: a bad histogram bucket
        # on /metrics links straight to a concrete flight-recorder trace
        metrics.observe(f"span.{name}.ms", dur_ms,
                        exemplar={"trace_id": trace_id})
        trace_store.record(SpanRecord(
            trace_id=trace_id, span_id=handle.span_id,
            parent_id=handle.parent_id, name=name, start_s=start_s,
            duration_ms=dur_ms, status=status, fields=handle.fields))
        log.info(json.dumps({"span": name, "trace": trace_id,
                             "status": status,
                             "duration_ms": round(dur_ms, 3),
                             **handle.fields}, ensure_ascii=False,
                            default=str))


# default cumulative-bucket bounds for span-duration histograms, in ms
# (Prometheus `le` upper bounds; +Inf is implicit). Chosen to straddle the
# measured pipeline: sub-ms bus hops up through multi-second cold compiles.
# Override per process via ObsConfig.histogram_buckets_ms (runner applies
# Metrics.set_bucket_bounds at boot — BEFORE traffic; bounds are fixed per
# histogram at first observation, rebucketing recorded data is impossible).
DEFAULT_BUCKET_BOUNDS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                            500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class _Histogram:
    __slots__ = ("values", "count", "total", "vmin", "vmax",
                 "bounds", "bucket_counts", "exemplars")

    def __init__(self, bounds: tuple = DEFAULT_BUCKET_BOUNDS_MS) -> None:
        self.values: list = []  # sorted reservoir (bounded)
        self.count = 0
        self.total = 0.0
        # exact running extremes: the reservoir decimation below drops
        # alternating samples (including, half the time, the true min) and
        # truncates tails — min/max must not ride the lossy reservoir
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        # real Prometheus histogram state: exact per-bucket counts (the
        # reservoir's quantiles cannot be aggregated across processes;
        # `_bucket`/`le` series can) + the latest exemplar seen per bucket
        # (value, {label: v}, unix ts) — a bad bucket links to a concrete
        # flight-recorder trace
        self.bounds: Tuple[float, ...] = tuple(bounds)
        self.bucket_counts: list = [0] * (len(self.bounds) + 1)
        self.exemplars: list = [None] * (len(self.bounds) + 1)

    def observe(self, v: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        self.count += 1
        self.total += v
        if self.vmin is None or v < self.vmin:
            self.vmin = v
        if self.vmax is None or v > self.vmax:
            self.vmax = v
        # non-cumulative bucket index; bisect_left keeps `le` INCLUSIVE
        # (v == bound counts in that bound's bucket, Prometheus semantics)
        b = bisect.bisect_left(self.bounds, v)
        self.bucket_counts[b] += 1
        if exemplar:
            self.exemplars[b] = (v, dict(exemplar), time.time())
        bisect.insort(self.values, v)
        if len(self.values) > 4096:
            # drop alternating samples to stay bounded but keep the shape
            del self.values[::2]

    def quantile(self, q: float) -> float:
        if not self.values:
            return 0.0
        idx = min(len(self.values) - 1, int(q * len(self.values)))
        return self.values[idx]

    def cumulative_buckets(self) -> list:
        """[(le_bound, cumulative_count), ...] ending with ("+Inf", count)."""
        out, running = [], 0
        for bound, n in zip(self.bounds, self.bucket_counts):
            running += n
            out.append((bound, running))
        out.append(("+Inf", running + self.bucket_counts[-1]))
        return out

    def summary(self) -> dict:
        return {"count": self.count,
                "sum": self.total,  # exact running total (renderers must
                                    # not reconstruct it as mean*count)
                "mean": self.total / self.count if self.count else 0.0,
                "min": self.vmin if self.vmin is not None else 0.0,
                "max": self.vmax if self.vmax is not None else 0.0,
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99),
                "buckets": self.cumulative_buckets(),
                "exemplars": list(self.exemplars)}


# label set normalized to a sorted tuple: one canonical key per
# (name, labels) pair regardless of caller dict ordering
_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_key(name: str, lk: _LabelKey) -> str:
    if not lk:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in lk)
    return f"{name}{{{inner}}}"


class Metrics:
    """Counters + histograms + gauges, each optionally labeled.

    Gauges come in two flavors: value gauges (`gauge_set`/`gauge_add` — e.g.
    live SSE clients) and callback gauges (`register_gauge` — evaluated at
    scrape time, e.g. batcher queue depth). A callback returning None (or
    raising) is dropped from the registry: callbacks close over weakrefs of
    engine/batcher instances, and a dead instance must disappear from the
    scrape instead of pinning the object or poisoning the snapshot."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, _LabelKey], float] = {}
        self._hists: Dict[Tuple[str, _LabelKey], _Histogram] = {}
        self._gauges: Dict[Tuple[str, _LabelKey], float] = {}
        self._gauge_fns: Dict[Tuple[str, _LabelKey], Callable] = {}
        self._bucket_bounds: Tuple[float, ...] = DEFAULT_BUCKET_BOUNDS_MS

    def set_bucket_bounds(self, bounds) -> None:
        """Cumulative-bucket upper bounds (`le`) for histograms created
        AFTER this call — existing histograms keep theirs (recorded samples
        cannot be rebucketed). The runner applies ObsConfig
        .histogram_buckets_ms here at boot, before traffic."""
        bounds = tuple(float(b) for b in bounds)
        if not bounds or any(b <= 0 for b in bounds) \
                or list(bounds) != sorted(set(bounds)):
            raise ValueError(
                "bucket bounds must be positive, strictly increasing and "
                f"non-empty, got {bounds!r}")
        with self._lock:
            self._bucket_bounds = bounds

    # ------------------------------------------------------------- counters

    def inc(self, name: str, n: float = 1,
            labels: Optional[Dict[str, str]] = None) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def get(self, name: str,
            labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0)

    # ----------------------------------------------------------- histograms

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        """`exemplar` is a tiny label dict (by convention `{"trace_id":
        ...}`) attached to the bucket this sample lands in — rendered as an
        OpenMetrics exemplar so a bad bucket links to a flight-recorder
        trace."""
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Histogram(self._bucket_bounds)
            h.observe(value, exemplar=exemplar)

    def histogram_summary(self, name: str,
                          labels: Optional[Dict[str, str]] = None
                          ) -> Optional[dict]:
        with self._lock:
            h = self._hists.get((name, _label_key(labels)))
            return h.summary() if h is not None else None

    def histogram_summaries(self, name: str) -> list:
        """Every labeled variant of one histogram family:
        [(labels_dict, summary), ...]. The SLO watchdog judges each variant
        separately — remote-role span durations federated by the fleet
        plane (obs/fleet.py) land as `{role: ...}`-labeled histograms, and
        a breach in ONE role must not hide inside a fleet-wide blend."""
        with self._lock:
            found = [(dict(lk), h.summary())
                     for (n, lk), h in self._hists.items() if n == name]
        return found

    # --------------------------------------------------------------- gauges

    def gauge_set(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[(name, _label_key(labels))] = value

    def gauge_add(self, name: str, delta: float,
                  labels: Optional[Dict[str, str]] = None) -> float:
        key = (name, _label_key(labels))
        with self._lock:
            v = self._gauges.get(key, 0) + delta
            self._gauges[key] = v
            return v

    def gauge_get(self, name: str,
                  labels: Optional[Dict[str, str]] = None) -> float:
        key = (name, _label_key(labels))
        with self._lock:
            if key in self._gauges:
                return self._gauges[key]
            fn = self._gauge_fns.get(key)
        if fn is None:
            return 0
        evaluated = self._eval_gauge_fns({key: fn})
        return evaluated.get(key, 0)

    def register_gauge(self, name: str, fn: Callable,
                       labels: Optional[Dict[str, str]] = None) -> None:
        """Callback gauge, read at scrape time. Re-registering the same
        (name, labels) replaces the callback (a fresh engine instance takes
        over its predecessor's gauge)."""
        with self._lock:
            self._gauge_fns[(name, _label_key(labels))] = fn

    def register_weakref_gauge(self, name: str, obj, reader: Callable,
                               labels: Optional[Dict[str, str]] = None
                               ) -> None:
        """Callback gauge bound to `obj` WITHOUT pinning it: the registry
        holds a weakref, `reader(obj)` produces the value, and when the
        owner dies (or the reader signals retirement by returning None) the
        gauge unregisters itself at the next scrape. The one place the
        owner-lifecycle contract lives — engine/batcher/LM gauges all go
        through here."""
        import weakref

        ref = weakref.ref(obj)

        def fn():
            o = ref()
            return None if o is None else reader(o)

        self.register_gauge(name, fn, labels=labels)

    def unregister_gauge(self, name: str,
                         labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauge_fns.pop((name, _label_key(labels)), None)

    def _eval_gauge_fns(self, fns: Dict) -> Dict:
        """Evaluate callback gauges OUTSIDE the registry lock (a callback may
        take an engine/batcher lock; holding ours too invites ordering
        deadlocks). A callback returning None is retired (the weakref-death
        convention); one that RAISES is skipped for this scrape but kept —
        a transient error (e.g. a racing collection mutation) must not
        silently delete a gauge for the life of the process."""
        out, dead = {}, []
        for key, fn in fns.items():
            try:
                v = fn()
            except Exception:
                log.debug("callback gauge %s failed this scrape", key[0],
                          exc_info=True)
                continue
            if v is None:
                dead.append(key)
            else:
                out[key] = v
        if dead:
            with self._lock:
                for key in dead:
                    self._gauge_fns.pop(key, None)
        return out

    # ------------------------------------------------------------ rendering

    def export(self) -> dict:
        """Structured dump for renderers: kind → [(name, labels-dict,
        value-or-summary)]. Callback gauges are evaluated here."""
        with self._lock:
            counters = list(self._counters.items())
            hists = [(k, h.summary()) for k, h in self._hists.items()]
            gauges = list(self._gauges.items())
            fns = dict(self._gauge_fns)
        gauges += list(self._eval_gauge_fns(fns).items())
        return {
            "counters": [(n, dict(lk), v) for (n, lk), v in counters],
            "histograms": [(n, dict(lk), s) for (n, lk), s in hists],
            "gauges": [(n, dict(lk), v) for (n, lk), v in gauges],
        }

    def snapshot(self) -> dict:
        """JSON-shaped view (api /api/metrics; BASELINE.md numbers). Labeled
        series render as `name{k="v"}` keys; unlabeled keep their bare name
        (the shape every pre-obs consumer knows)."""
        ex = self.export()
        return {
            "counters": {_render_key(n, _label_key(lb)): v
                         for n, lb, v in ex["counters"]},
            # exemplars (trace-id samples) are an exposition-format detail;
            # the JSON view keeps stats + buckets only
            "histograms": {_render_key(n, _label_key(lb)):
                           {k: v for k, v in s.items() if k != "exemplars"}
                           for n, lb, s in ex["histograms"]},
            "gauges": {_render_key(n, _label_key(lb)): v
                       for n, lb, v in ex["gauges"]},
        }

    def flat_snapshot(self) -> Dict[str, float]:
        """One flat string→number dict (archived into bench JSON so
        BENCH_*.json carries the internal gauges, not just external
        timings). Histograms contribute count/p50/p99/min/max."""
        snap = self.snapshot()
        flat: Dict[str, float] = {}
        for k, v in snap["counters"].items():
            flat[f"counter.{k}"] = v
        for k, v in snap["gauges"].items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                flat[f"gauge.{k}"] = float(v)
        for k, s in snap["histograms"].items():
            for stat in ("count", "p50", "p99", "min", "max"):
                flat[f"hist.{k}.{stat}"] = float(s[stat])
        return flat

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()
            self._gauges.clear()
            self._gauge_fns.clear()


metrics = Metrics()
