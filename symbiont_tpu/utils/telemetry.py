"""Tracing + metrics — the observability layer the reference lacks.

Reference state (SURVEY.md §5.1/§5.5): bare env_logger lines with bracket tags,
ids carried only inside payloads, NATS monitoring port exposed but unscraped,
zero counters. Here:

- Trace: every message carries trace/span ids in bus headers
  (X-Trace-Id/X-Span-Id); `child_headers` propagates across hops; `span`
  times a handler and logs a structured line.
- Metrics: process-global registry of counters and histograms (p50/p95/p99),
  rendered as JSON (api /api/metrics) — these produce the BASELINE.md numbers
  (per-subject consumed/published/failed, embed throughput, search latency).
"""

from __future__ import annotations

import bisect
import json
import logging
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from symbiont_tpu.utils.ids import generate_uuid

log = logging.getLogger("symbiont.trace")

TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"


def new_trace_headers() -> Dict[str, str]:
    return {TRACE_HEADER: generate_uuid(), SPAN_HEADER: generate_uuid()}


def child_headers(parent: Optional[Dict[str, str]]) -> Dict[str, str]:
    """Same trace, fresh span; starts a new trace when no parent context."""
    if not parent or TRACE_HEADER not in parent:
        return new_trace_headers()
    return {TRACE_HEADER: parent[TRACE_HEADER], SPAN_HEADER: generate_uuid()}


_profile_lock = threading.Lock()


@contextmanager
def maybe_profile(name: str):
    """Device-level profiling hook (SURVEY.md §5.1 plan: "JAX profiler around
    the embed/decode steps"). When SYMBIONT_PROFILE_DIR is set, the wrapped
    compute runs under `jax.profiler.trace` and the XPlane trace lands there
    (view with TensorBoard's profile plugin / xprof). Off (the default) this
    is a no-op with zero per-call cost beyond one env lookup.

    Intended use: operator sets the env var on the engine process for a short
    diagnosis window; every embed / rerank / decode call in that window
    produces a trace annotated with `name`.

    The JAX profiler is process-global and non-reentrant ("Only one profile
    may be run at a time"); embed / rerank / generate can overlap across
    threads, so a call that finds a profile already running proceeds
    unprofiled rather than crashing the live request."""
    import os

    d = os.environ.get("SYMBIONT_PROFILE_DIR")
    if not d or not _profile_lock.acquire(blocking=False):
        yield
        return
    try:
        import jax

        with jax.profiler.trace(d):
            with jax.profiler.TraceAnnotation(name):
                yield
    finally:
        _profile_lock.release()


@contextmanager
def span(name: str, headers: Optional[Dict[str, str]] = None, **fields):
    """Timed span with structured log line (duration_ms, trace id, extras)."""
    t0 = time.perf_counter()
    trace_id = (headers or {}).get(TRACE_HEADER, "-")
    try:
        yield
        status = "ok"
    except Exception:
        status = "error"
        raise
    finally:
        dur_ms = (time.perf_counter() - t0) * 1000
        metrics.observe(f"span.{name}.ms", dur_ms)
        log.info(json.dumps({"span": name, "trace": trace_id, "status": status,
                             "duration_ms": round(dur_ms, 3), **fields},
                            ensure_ascii=False))


class _Histogram:
    __slots__ = ("values", "count", "total")

    def __init__(self) -> None:
        self.values: list = []  # sorted reservoir (bounded)
        self.count = 0
        self.total = 0.0

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        bisect.insort(self.values, v)
        if len(self.values) > 4096:
            # drop alternating samples to stay bounded but keep the shape
            del self.values[::2]

    def quantile(self, q: float) -> float:
        if not self.values:
            return 0.0
        idx = min(len(self.values) - 1, int(q * len(self.values)))
        return self.values[idx]

    def summary(self) -> dict:
        return {"count": self.count,
                "mean": self.total / self.count if self.count else 0.0,
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._hists: Dict[str, _Histogram] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists.setdefault(name, _Histogram()).observe(value)

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self._counters),
                    "histograms": {k: h.summary() for k, h in self._hists.items()}}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()


metrics = Metrics()
