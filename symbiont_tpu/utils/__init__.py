from symbiont_tpu.utils.ids import current_timestamp_ms, generate_uuid

__all__ = ["current_timestamp_ms", "generate_uuid"]
