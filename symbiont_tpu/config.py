"""Typed configuration layer: defaults < config file < environment.

The reference has no config system — raw `std::env::var` calls with warn+default
fallbacks scattered through every service plus hardcoded constants (SURVEY.md
§5.6; e.g. reference: services/perception_service/src/main.rs:177-180, batch
size 8 at services/preprocessing_service/src/embedding_generator.rs:146). Here
every tunable lives in one typed tree shared by the Python engine/services and
exported to the native C++ workers via environment variables.

Env override convention: SYMBIONT_<SECTION>_<FIELD>, e.g.
SYMBIONT_ENGINE_MODEL_NAME, SYMBIONT_BUS_URL. Reference-era env names
(NATS_URL, QDRANT_URI, FORCE_CPU, API_SERVER_HOST/PORT) are honored as aliases
for drop-in compatibility (reference: .env.example:1-12).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, List, Optional

# Weight-quantization modes (docs/QUANTIZATION.md). THE single source:
# models/quant.py re-exports this as quant.MODES — defined here because
# config must stay importable without jax (CPU-only doc rendering).
QUANTIZE_MODES = ("none", "f16", "int8", "fp8")


@dataclass
class BusConfig:
    # reference default: nats://localhost:4222 (services) / nats://cs-nats:4222
    # (api_service) — reference: services/api_service/src/main.rs:519-524.
    # Ours defaults to the in-process bus (single-process stack needs no
    # broker); set symbus://host:port to go through the native broker.
    url: str = "inproc://"
    request_timeout_embed_s: float = 15.0  # reference: api_service/src/main.rs:310
    request_timeout_search_s: float = 20.0  # reference: api_service/src/main.rs:430
    # rerank hop (our addition — the reference has no rerank stage)
    request_timeout_rerank_s: float = 10.0
    # engine.health hop behind GET /api/health/engine (our addition)
    request_timeout_health_s: float = 5.0
    # at-least-once pipeline: durable streams on the native broker (SURVEY.md
    # §5.3 — the reference's core NATS silently loses in-flight work). Only
    # effective on symbus:// transports; the in-proc bus stays at-most-once.
    durable: bool = False
    durable_ack_wait_s: float = 60.0
    durable_max_deliver: int = 5

    def __post_init__(self) -> None:
        if self.durable_ack_wait_s <= 0:
            raise ValueError("bus.durable_ack_wait_s must be positive")
        if self.durable_max_deliver < 1:
            raise ValueError("bus.durable_max_deliver must be >= 1")


@dataclass
class EngineConfig:
    # reference hardcodes the model id twice
    # (reference: services/preprocessing_service/src/main.rs:305 and :121)
    model_name: str = "sentence-transformers/paraphrase-multilingual-mpnet-base-v2"
    model_dir: Optional[str] = None  # local checkpoint dir (safetensors + config)
    embedding_dim: int = 768
    force_cpu: bool = False  # reference: FORCE_CPU env, preprocessing main.rs:307
    dtype: str = "bfloat16"
    # attention backend: "auto" → XLA fused attention (fastest at every
    # measured encoder bucket on v5e with the bf16 softmax path);
    # "flash" opts into the pallas kernel (no S² intermediates — the
    # memory-bound choice); "xla" forces XLA.
    attn_impl: str = "auto"
    # Length buckets replace the reference's pad-everything-to-max policy
    # (reference: embedding_generator.rs:83-91) — §5.7 of SURVEY.md.
    length_buckets: List[int] = field(default_factory=lambda: [32, 64, 128, 256, 512])
    # Batch buckets: one compiled executable per (length bucket, batch bucket).
    batch_buckets: List[int] = field(default_factory=lambda: [1, 8, 32, 128])
    max_batch: int = 128
    # Interactive path: flush a partial batch after this deadline.
    flush_deadline_ms: float = 5.0
    # Micro-batcher flushes dispatched concurrently: on a network-attached
    # device each flush tail is ~an RTT of pure waiting, so overlapping
    # flushes keeps the chip fed (engine/batcher.py _BatcherBase). 2 was
    # measured as break-even locally; raise toward 4 on a high-RTT tunnel.
    max_inflight_flushes: int = 2
    # Engine-plane tenant fairness (engine/batcher.TenantLanes): items queue
    # in per-tenant lanes drained stride-fair, so a hot tenant that bypasses
    # the API edge cannot starve others at the device queue. This bounds
    # each lane; a full lane rejects (typed engine error / unacked durable
    # delivery that redelivers later) instead of growing without limit.
    # 0 = unbounded lanes (fairness still applies).
    tenant_lane_depth: int = 4096
    data_parallel: bool = True  # shard batches across the mesh 'data' axis
    executable_cache_size: int = 64
    # Bulk-ingest host pipeline: embed_texts tokenizes this many texts per
    # chunk on a background thread while the main thread pads/dispatches the
    # previous chunk (two-deep prep queue) — host prep of chunk N+1 overlaps
    # device compute + transfers of chunk N. 0 disables chunking (tokenize
    # everything up front, the pre-r4 behavior).
    host_prep_chunk: int = 2048
    # Cross-encoder rerank (BASELINE.md config #4: ms-marco-MiniLM-L-6 on
    # top-k hits). cross_model_dir points at a converted checkpoint;
    # rerank_enabled without a dir runs a synthetic cross-encoder (random
    # weights, embedder geometry) so the full rerank path works asset-free.
    cross_model_dir: Optional[str] = None
    rerank_enabled: bool = False
    # Weight quantization at load time (models/quant.py, ROADMAP item 4):
    # "none" keeps f32-at-rest storage; "f16" stores rank-≥2 params bf16
    # (halves every weight read — the forward already computes bf16);
    # "int8" / "fp8" store symmetric per-channel quantized kernels with
    # dequant fused into the matmuls. Parity bars in docs/QUANTIZATION.md,
    # gated by tests/test_quantization.py and the bench quant tier.
    quantize: str = "none"

    def __post_init__(self) -> None:
        if self.quantize not in QUANTIZE_MODES:
            raise ValueError(
                f"engine.quantize must be one of {QUANTIZE_MODES}, "
                f"got {self.quantize!r}")
        if self.tenant_lane_depth < 0:
            raise ValueError("engine.tenant_lane_depth must be >= 0")


@dataclass
class LmConfig:
    """Decoder-LM generation (BASELINE.md config #5). Off by default: the
    reference-parity Markov backend serves tasks.generation.text until this
    is enabled (reference: text_generator_service/src/main.rs:13-109)."""

    enabled: bool = False
    model_dir: Optional[str] = None  # GPT-2/Llama checkpoint dir (safetensors)
    # synthetic-mode geometry (used when model_dir is None; byte-level vocab)
    arch: str = "llama"
    hidden_size: int = 512
    num_layers: int = 8
    num_heads: int = 8
    intermediate_size: int = 1536
    max_positions: int = 2048
    dtype: str = "bfloat16"
    attn_impl: str = "auto"
    # tensor-parallel serving decode over the stack mesh's 'tensor' axis.
    # "auto" shards when the head/ffn counts divide the axis and falls back
    # to single-device placement (with a warning) when they don't — a mesh
    # whose tensor axis exists for the encoder/training must not brick LM
    # boot. "on" makes non-divisibility a hard error; "off" never shards.
    tensor_parallel: str = "auto"
    # static-shape buckets: one decode executable per (prompt, new) pair
    prompt_buckets: List[int] = field(default_factory=lambda: [16, 64, 256, 1024])
    new_token_buckets: List[int] = field(default_factory=lambda: [16, 64, 128, 256, 1024])
    temperature: float = 0.8
    top_k: int = 40
    seed: int = 0
    # generation micro-batching: concurrent generate requests within the
    # flush window decode as one batched call (engine/batcher.GenBatcher).
    # The window matters more than for embeddings: a newcomer whose budget
    # EQUALS the session's new-token bucket can never join mid-flight
    # (its budget always exceeds the remaining steps), so same-budget
    # request waves batch only if they land in one window — 30 ms of
    # added first-token latency vs multi-second decodes is the right
    # trade (measured r5: a 16-client wave missing the window fragmented
    # into per-request sessions, 10x the wall time).
    gen_max_batch: int = 8
    gen_flush_deadline_ms: float = 30.0
    # per-tenant bounded lanes in front of the generation batcher (see
    # EngineConfig.tenant_lane_depth; generation requests are heavier, so
    # the default lane bound is tighter). 0 = unbounded.
    gen_tenant_lane_depth: int = 1024
    # continuous batching: a decode session keeps at least this many batch
    # rows so requests arriving mid-decode can JOIN at chunk boundaries
    # (BatchSession.admit). Nearly free on TPU — decode steps are bound by
    # weight reads, which all rows share.
    session_min_rows: int = 4
    # token streaming (events.text.generated.partial): decode in chunks of
    # this many tokens, emitting a text delta per chunk; 0 disables streaming
    stream_chunk: int = 16
    # Weight quantization at load time (models/quant.py; same modes and
    # parity bars as EngineConfig.quantize). Applied by _place_params on
    # every parameter placement — including online fine-tune syncs, whose
    # f32 masters re-quantize on each update_params. Composes with TP
    # decode: QuantTensor codes shard on the kernel's own axes and the
    # per-output-channel scales ride the same axis (parallel/sharding.py),
    # so `quantize=int8` + `tensor>1` serves sharded AND narrow.
    quantize: str = "none"
    # KV-cache storage for decode sessions: "none" keeps cfg.dtype slabs;
    # "int8" stores per-(position, head)-scaled int8 K/V — quantize-on-
    # append, dequant-on-attend inside the compiled decode step, so a
    # session holds ~2× more rows per HBM byte vs bf16 (~4× vs f32) at the
    # cost of ~0.4% K/V rounding (greedy-identity gate:
    # tests/test_quantization.py).
    kv_quant: str = "none"
    # KV-cache LAYOUT for continuous-batching decode sessions (the paged KV
    # subsystem, symbiont_tpu/kv/ — docs/KV.md). "dense" keeps one
    # max-length slab per session row (the pre-paged behavior); "paged"
    # stores K/V in fixed-size pages drawn from a preallocated device pool
    # (kv/pool.py) gathered into attention via a per-row page table, so a
    # session occupies pages proportional to tokens actually decoded
    # instead of its worst-case slab. Token-identical to dense across
    # kv_quant modes (tests/test_kv_paged.py); composes with kv_quant=int8
    # (int8 page pools + f32 scale pools).
    kv_layout: str = "dense"
    # tokens per KV page. Must divide every prompt bucket so the prompt
    # region of a row is whole pages (the radix cache shares at page
    # granularity and decode writes never land in a shared prompt page).
    # Smaller pages waste less on short sessions but grow the page table.
    kv_page_tokens: int = 16
    # device pool size in pages; 0 = auto (dense-equivalent capacity for
    # one max-geometry session batch, ×2 headroom for radix retention).
    kv_pool_pages: int = 0
    # refcounted radix prefix cache over committed prompt pages
    # (kv/radix.py): admits whose prompts share a cached prefix reuse the
    # committed pages (refcount++) instead of re-materializing them, and a
    # FULL-prompt hit skips its prefill entirely (TTFT collapses to ~one
    # decode chunk). Refcount-0 pages are retained and evicted LRU under
    # pool pressure. Only meaningful with kv_layout="paged".
    kv_radix: bool = True
    # Speculative decoding (docs/SPECULATIVE.md): a small draft model
    # proposes spec_k greedy tokens per round on its own dense KV, the
    # target scores all k+1 positions in ONE verify dispatch, and the
    # longest exact-match prefix plus the target's corrected token is
    # emitted — greedy output is token-identical to plain decode by
    # construction; sampled output rides the same journalled PRNG chain.
    # spec_draft_model points at a local HF checkpoint dir for the
    # drafter (tokenizer + vocab must match the target — validated at
    # boot, jax-free, by validate_spec_draft below). None disables; a
    # missing dir degrades to spec-disabled with one warning.
    spec_draft_model: Optional[str] = None
    spec_k: int = 8  # draft tokens proposed per verification round
    # online fine-tune over ingested text (train/online.py): the LM analog of
    # the Markov backend's continuous learning. Off by default — training
    # shares the device with serving.
    ingest_train: bool = False
    ingest_train_steps: int = 2       # optimizer steps per training pass
    ingest_train_min_chars: int = 512  # buffer this much text before a pass
    ingest_train_seq_len: int = 64
    ingest_train_batch: int = 8
    ingest_train_lr: float = 1e-4
    train_state_path: Optional[str] = None  # persist/resume learning

    def __post_init__(self) -> None:
        if self.tensor_parallel not in ("auto", "on", "off"):
            raise ValueError(
                f"tensor_parallel must be auto|on|off, "
                f"got {self.tensor_parallel!r}")
        if self.quantize not in QUANTIZE_MODES:
            raise ValueError(
                f"lm.quantize must be one of {QUANTIZE_MODES}, "
                f"got {self.quantize!r}")
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"lm.kv_quant must be none|int8, got {self.kv_quant!r}")
        if self.kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"lm.kv_layout must be dense|paged, got {self.kv_layout!r}")
        if self.kv_layout == "paged":
            if self.kv_page_tokens < 1:
                raise ValueError("lm.kv_page_tokens must be >= 1")
            bad = [b for b in self.prompt_buckets
                   if b % self.kv_page_tokens]
            if bad:
                # prompt region must be whole pages: the radix cache shares
                # committed prompt pages between sessions, and a page
                # straddling the prompt/decode boundary would receive
                # per-session decode writes — unshareable by construction
                raise ValueError(
                    f"kv_page_tokens={self.kv_page_tokens} must divide "
                    f"every prompt bucket; offending buckets: {bad}")
            if self.kv_pool_pages < 0:
                raise ValueError("lm.kv_pool_pages must be >= 0 (0 = auto)")
        if self.gen_tenant_lane_depth < 0:
            raise ValueError("lm.gen_tenant_lane_depth must be >= 0")
        if self.spec_k < 1:
            raise ValueError(f"lm.spec_k must be >= 1, got {self.spec_k}")
        # the streaming decode loop runs whole chunks against a KV cache with
        # exactly new_bucket decode slots — a non-dividing chunk would scan
        # past the cache and rely on dynamic_update_slice clamp semantics
        if self.stream_chunk > 0:
            bad = [b for b in self.new_token_buckets
                   if b > self.stream_chunk and b % self.stream_chunk]
            if bad:
                raise ValueError(
                    f"stream_chunk={self.stream_chunk} must divide every "
                    f"new_token_bucket larger than it; offending buckets: {bad}")


def validate_spec_draft(target_dir: str, draft_dir: str) -> None:
    """Boot-time drafter/target compatibility check (jax-free).

    Speculative decoding only works when the draft and target models
    speak the SAME token ids: the verify dispatch scores the drafter's
    token ids directly against the target's logits. Enforced here so an
    incompatible pair fails at engine init with a clear error instead of
    emitting garbage mid-stream. Checks, from the HF checkpoint dirs:

    - `config.json` vocab_size parity (hard requirement), and
    - tokenizer parity by content fingerprint (`tokenizer.json`, else
      `vocab.json`) when BOTH dirs carry one — same vocab_size with a
      different id->string mapping is still wrong.

    Raises ValueError on mismatch. Existence of draft_dir is the
    CALLER's concern (engine init warns + disables on a missing dir).
    """
    import hashlib

    def _vocab(d: str) -> int:
        p = Path(d) / "config.json"
        try:
            return int(json.loads(p.read_text()).get("vocab_size", -1))
        except (OSError, ValueError) as e:
            raise ValueError(f"spec_draft_model compat: cannot read {p}: {e}")

    tv, dv = _vocab(target_dir), _vocab(draft_dir)
    if tv != dv:
        raise ValueError(
            f"spec_draft_model vocab mismatch: target {target_dir!r} has "
            f"vocab_size={tv} but draft {draft_dir!r} has vocab_size={dv} "
            f"— speculative verification compares token ids directly, so "
            f"drafter and target must share one tokenizer/vocab")

    def _tok_fp(d: str) -> Optional[str]:
        for name in ("tokenizer.json", "vocab.json"):
            p = Path(d) / name
            if p.is_file():
                return name + ":" + hashlib.sha256(p.read_bytes()).hexdigest()
        return None

    tf, df = _tok_fp(target_dir), _tok_fp(draft_dir)
    if tf is not None and df is not None and tf != df:
        raise ValueError(
            f"spec_draft_model tokenizer mismatch: target {target_dir!r} "
            f"and draft {draft_dir!r} carry different tokenizer files "
            f"({tf.split(':')[0]} fingerprints differ) — draft token ids "
            f"would not mean the same strings under the target")


@dataclass
class VectorStoreConfig:
    # reference: collection name + dim 768 + cosine hardcoded
    # (reference: services/vector_memory_service/src/main.rs:20-22,34-42)
    # uri accepted for reference-deployment compat (QDRANT_URI); the embedded
    # TPU-native store ignores it unless an external-qdrant backend is selected.
    uri: Optional[str] = None
    collection: str = "symbiont_document_embeddings"
    dim: int = 768
    distance: str = "cosine"
    data_dir: str = "data/vector_store"
    device_resident: bool = True  # corpus matrix lives in TPU HBM
    shard_capacity: int = 65536  # rows per device-resident block
    # warm_fused pre-compiles the fused embed+top-k executables for every
    # power-of-two k bucket up to this value. Must cover the gateway's
    # ApiConfig.fused_search_max_top_k (default 16) — a fused query in an
    # unwarmed bucket pays a cold XLA compile inside the probe timeout
    warm_top_k: int = 16
    # Cross-message upsert coalescing (services/coalesce.py): the Python
    # vector-memory worker batches rows from many data.text.with_embeddings
    # messages into ONE upsert_rows call, acking each durable delivery only
    # after the flush carrying its rows commits. Flush fires at
    # coalesce_max_rows pending rows or when the oldest row has waited
    # coalesce_max_age_ms (also on shutdown). The age bound caps the added
    # ack latency; keep it well below bus.durable_ack_wait_s.
    coalesce: bool = True
    coalesce_max_rows: int = 512
    coalesce_max_age_ms: float = 25.0

    def __post_init__(self) -> None:
        if self.coalesce_max_rows < 1:
            raise ValueError("vector_store.coalesce_max_rows must be >= 1")
        if self.coalesce_max_age_ms <= 0:
            raise ValueError(
                "vector_store.coalesce_max_age_ms must be positive")


@dataclass
class GraphStoreConfig:
    data_dir: str = "data/graph_store"
    # External Neo4j backend (reference-migration deployments): set uri to
    # the Neo4j HTTP API endpoint (http://host:7474) and the runner swaps in
    # the Neo4j adapter; the embedded sqlite store is the default.
    # Reference env aliases NEO4J_URI/USER/PASSWORD map here.
    uri: Optional[str] = None
    user: str = "neo4j"
    password: str = "password"
    database: str = "neo4j"


@dataclass
class ApiConfig:
    # reference: API_SERVER_HOST/PORT (reference: api_service/src/main.rs:545-547)
    host: str = "127.0.0.1"
    port: int = 8080
    sse_keepalive_s: float = 15.0  # reference: api_service/src/main.rs:190-213
    sse_channel_capacity: int = 32  # reference: api_service/src/main.rs:537
    max_gen_length: int = 1000  # reference: api_service/src/main.rs:133
    # try the fused embed+top-k engine hop first (one device round-trip);
    # fall back to the reference's 2-hop embed→search orchestration when the
    # fused subject isn't served (engine and store in separate processes)
    fused_search: bool = True
    fused_search_timeout_s: float = 5.0
    # after a fused timeout, skip the fused probe for this long (the subject
    # is unserved when engine and store are not co-located)
    fused_search_down_s: float = 60.0
    # fused serves the interactive small-k range its executables are
    # pre-warmed for; larger top_k goes straight to the 2-hop path instead
    # of paying a cold XLA compile inside the probe timeout and tripping the
    # negative cache. Raise together with VectorStoreConfig.warm_top_k —
    # the engine warms every power-of-two k bucket up to that value
    fused_search_max_top_k: int = 16


@dataclass
class TextGeneratorConfig:
    """Markov-backend persistence (SURVEY.md §5.4): the reference rebuilds
    its chain from one hardcoded sentence at every boot, losing all learned
    state (reference: text_generator_service/src/main.rs:169-173). Here the
    chain persists across restarts; None disables."""

    markov_state_path: Optional[str] = "data/markov_state.json"


@dataclass
class PerceptionConfig:
    scrape_timeout_s: float = 15.0  # reference: perception_service/src/main.rs:89-91
    user_agent: str = "SymbiontTPU/0.1 (+research crawler)"


@dataclass
class ParallelConfig:
    """The live stack's device mesh (docs/SCALING.md, ROADMAP item 1).

    The runner builds ONE mesh from this section at stack start and threads
    it through TpuEngine (DP embed over 'data'), LmEngine (TP decode over
    'tensor') and the embedded vector store (corpus rows sharded over
    'data') — going multi-chip is a config change, not a code change.
    SYMBIONT_PARALLEL_MESH_SHAPE='[4, 2]' is the env spelling of dp4xtp2."""

    # serve from a mesh at all; off → every engine gets mesh=None (the
    # pre-mesh single-chip behavior, byte-identical executables)
    enabled: bool = True
    # Mesh axes: data / tensor. PP/SP axes are pluggable (SURVEY.md §2 table).
    mesh_shape: Optional[List[int]] = None  # None → (n_devices, 1)
    axis_names: List[str] = field(default_factory=lambda: ["data", "tensor"])

    def __post_init__(self) -> None:
        if self.mesh_shape is not None:
            if (not self.mesh_shape
                    or any(int(s) < 1 for s in self.mesh_shape)):
                raise ValueError(
                    f"parallel.mesh_shape must be positive ints, "
                    f"got {self.mesh_shape!r}")
            if len(self.mesh_shape) != len(self.axis_names):
                raise ValueError(
                    f"parallel.mesh_shape {self.mesh_shape} must name one "
                    f"size per axis in {self.axis_names}")


@dataclass
class ObsConfig:
    """Observability (symbiont_tpu/obs/): flight-recorder sizing and the
    SLO watchdog. Thresholds are "span.name=p99_ms" entries, e.g.
    SYMBIONT_OBS_SLO_P99_MS='["api.search=500", "preprocessing.handle=2000"]'
    — the watchdog task only runs when at least one is configured."""

    # span records kept in the in-process flight recorder ring
    trace_capacity: int = 4096
    # Tail-based trace retention (obs/trace_store.py): errored /
    # SLO-breach-exemplar / slowest-decile traces PIN into a bounded
    # keep-set the ring's FIFO churn cannot evict (up to trace_keep_traces
    # of them), while healthy traces sample at trace_sample_rate (1.0 =
    # record every trace, the historical behavior; 0.1 = every 10th new
    # trace — pinned traces always record in full).
    trace_sample_rate: float = 1.0
    trace_keep_traces: int = 64
    # Decode-plane flight recorder (obs/engine_timeline.py): per-step
    # engine events kept in the bounded timeline ring (0 disables
    # recording), and how many recent prompt prefixes the admission-time
    # prefix-share probe compares against (lm.prefix_share_ratio).
    timeline_capacity: int = 2048
    timeline_prompt_window: int = 64
    # Per-tenant usage metering (obs/usage.py): distinct tenant identities
    # the ledger tracks — past the bound, new identities share the
    # "(overflow)" ledger (the admission plane's resolve_tenant stance).
    usage_max_tenants: int = 1024
    # seconds between SLO evaluations
    slo_interval_s: float = 10.0
    # two-window burn rates on SLO breach events (obs/watchdog.py): the
    # fast window catches a blip, the slow window proves a sustained burn
    # — the discriminator the elastic autoscaler's SLO signal reads
    slo_burn_fast_s: float = 60.0
    slo_burn_slow_s: float = 600.0
    # "span_name=p99_ms" entries evaluated against span.<name>.ms histograms
    slo_p99_ms: List[str] = field(default_factory=list)
    # cumulative-bucket upper bounds (`le`, in ms) for the span-duration
    # histogram family on /metrics; empty keeps
    # telemetry.DEFAULT_BUCKET_BOUNDS_MS. Applied by the runner at boot —
    # bounds are fixed per histogram at first observation.
    histogram_buckets_ms: List[float] = field(default_factory=list)
    # Fleet telemetry plane (obs/fleet.py, docs/OBSERVABILITY.md "Fleet
    # telemetry"): when this process runs as a named role in a supervised
    # multi-process deployment (runner.role set, or heartbeats on), it
    # publishes bounded metric-snapshot deltas + completed span records on
    # `_sys.telemetry.{metrics,spans}.<role>` every fleet_publish_s; the
    # API-role process hosts the FleetAggregator that merges them into one
    # federated /metrics exposition (role label), stitched cross-process
    # traces, and GET /api/fleet. Telemetry is SAMPLED under backpressure
    # and dropped-with-a-counter, never queued unboundedly — it must not
    # compete with the data path.
    fleet_export: bool = True
    fleet_publish_s: float = 2.0
    # spans carried per publish; the pending ring holds fleet_pending_max
    # finished spans between publishes (overflow counted in
    # fleet.spans_dropped — sampling, not queueing)
    fleet_spans_max: int = 256
    fleet_pending_max: int = 2048
    # metric delta entries per publish (overflow counted + retried next
    # round via the delta mechanism itself)
    fleet_metrics_max: int = 4096
    # every Nth metrics publish is a FULL snapshot (a late-joining
    # aggregator converges within full_every x publish_s)
    fleet_full_every: int = 15
    # distinct roles the aggregator tracks; past the bound new roles are
    # counted in fleet.role_overflow and ignored (client-suppliable role
    # names must not grow unbounded state)
    fleet_roles_max: int = 64
    # Compute-plane profiler (obs/xprof.py): the per-executable dispatch
    # ledger behind xla.dispatches_total / GET /api/engine/executables
    # (xprof_enabled=False turns every note into a cheap early return),
    # its LRU bound on distinct executables tracked, and the on-demand
    # device trace capture (POST /api/profile/device): hard cap on one
    # capture window and where trace artifacts land.
    xprof_enabled: bool = True
    xprof_executables: int = 256
    xprof_trace_max_s: float = 30.0
    xprof_trace_dir: str = "/tmp/symbiont_xprof"
    # HBM attribution plane (obs/hbm.py): the subsystem byte ledger /
    # live-array census behind GET /api/memory (+ /census) and the OOM
    # forensics postmortems (hbm_enabled=False disables ledger rows and
    # postmortem writes; engine.oom_total still counts). census_groups
    # bounds (shape, dtype, sharding) rows carried per census response;
    # postmortems land in postmortem_dir, newest postmortem_max kept.
    hbm_enabled: bool = True
    hbm_census_groups: int = 64
    hbm_postmortem_dir: str = "/tmp/symbiont_hbm"
    hbm_postmortem_max: int = 4

    def __post_init__(self) -> None:
        if self.trace_capacity < 1:
            raise ValueError("obs.trace_capacity must be >= 1")
        if not 0.0 < self.trace_sample_rate <= 1.0:
            raise ValueError("obs.trace_sample_rate must be in (0, 1]")
        if self.trace_keep_traces < 1:
            raise ValueError("obs.trace_keep_traces must be >= 1")
        if self.timeline_capacity < 0:
            raise ValueError("obs.timeline_capacity must be >= 0")
        if self.timeline_prompt_window < 1:
            raise ValueError("obs.timeline_prompt_window must be >= 1")
        if self.usage_max_tenants < 1:
            raise ValueError("obs.usage_max_tenants must be >= 1")
        if self.slo_interval_s <= 0:
            raise ValueError("obs.slo_interval_s must be positive")
        if self.slo_burn_fast_s <= 0 \
                or self.slo_burn_slow_s < self.slo_burn_fast_s:
            raise ValueError(
                "obs.slo_burn_fast_s must be positive and <= "
                "obs.slo_burn_slow_s")
        if self.fleet_publish_s <= 0:
            raise ValueError("obs.fleet_publish_s must be positive")
        for name in ("fleet_spans_max", "fleet_pending_max",
                     "fleet_metrics_max", "fleet_full_every",
                     "fleet_roles_max"):
            if getattr(self, name) < 1:
                raise ValueError(f"obs.{name} must be >= 1")
        if self.histogram_buckets_ms:
            b = self.histogram_buckets_ms
            if any(x <= 0 for x in b) or list(b) != sorted(set(b)):
                raise ValueError(
                    "obs.histogram_buckets_ms must be positive and "
                    "strictly increasing")
        if self.xprof_executables < 1:
            raise ValueError("obs.xprof_executables must be >= 1")
        if self.xprof_trace_max_s <= 0:
            raise ValueError("obs.xprof_trace_max_s must be positive")
        if not self.xprof_trace_dir:
            raise ValueError("obs.xprof_trace_dir must be non-empty")
        if self.hbm_census_groups < 1:
            raise ValueError("obs.hbm_census_groups must be >= 1")
        if self.hbm_postmortem_max < 1:
            raise ValueError("obs.hbm_postmortem_max must be >= 1")
        if not self.hbm_postmortem_dir:
            raise ValueError("obs.hbm_postmortem_dir must be non-empty")
        # malformed SLO entries fail at boot, not silently never fire
        from symbiont_tpu.obs.watchdog import parse_thresholds

        parse_thresholds(self.slo_p99_ms)


@dataclass
class ResilienceConfig:
    """Resilience plane (symbiont_tpu/resilience/, docs/RESILIENCE.md):
    handler timeouts/retries, store circuit breakers with WAL spill, the
    dead-letter quarantine, and loop-supervisor backoff."""

    # per-handler deadline; the handler is CANCELLED at the deadline and a
    # durable delivery stays unacked for redelivery. 0 disables (default:
    # first-call XLA compiles can legitimately take minutes on a cold
    # engine; production deployments should set an explicit budget).
    handler_timeout_s: float = 0.0
    # in-process retries for a FAILED (not timed-out) handler, with
    # full-jitter exponential backoff between attempts
    handler_retries: int = 0
    handler_backoff_base_s: float = 0.05
    handler_backoff_max_s: float = 2.0
    # circuit breakers around the EXTERNAL store backends (Qdrant/Neo4j):
    # after `breaker_failure_threshold` consecutive failures the breaker
    # opens, writes spill to a local WAL (replayed on recovery), and a
    # half-open probe is admitted every `breaker_reset_timeout_s`
    breaker_enabled: bool = True
    breaker_failure_threshold: int = 5
    breaker_reset_timeout_s: float = 30.0
    # spill WAL directory for breaker-degraded writes
    spill_dir: str = "data/resilience"
    # dead-letter quarantine ring size (inproc durable bus; GET /api/dlq)
    dlq_capacity: int = 256
    # restart backoff for crashed service dispatch loops
    supervisor_backoff_base_s: float = 0.5
    supervisor_backoff_max_s: float = 30.0

    def __post_init__(self) -> None:
        if self.handler_timeout_s < 0:
            raise ValueError("resilience.handler_timeout_s must be >= 0")
        if self.handler_retries < 0:
            raise ValueError("resilience.handler_retries must be >= 0")
        if (self.handler_backoff_base_s <= 0
                or self.handler_backoff_max_s < self.handler_backoff_base_s):
            raise ValueError(
                "resilience.handler_backoff_base_s must be positive and "
                "<= handler_backoff_max_s")
        if self.breaker_failure_threshold < 1:
            raise ValueError(
                "resilience.breaker_failure_threshold must be >= 1")
        if self.breaker_reset_timeout_s <= 0:
            raise ValueError(
                "resilience.breaker_reset_timeout_s must be positive")
        if self.dlq_capacity < 1:
            raise ValueError("resilience.dlq_capacity must be >= 1")
        if (self.supervisor_backoff_base_s <= 0
                or self.supervisor_backoff_max_s
                < self.supervisor_backoff_base_s):
            raise ValueError(
                "resilience.supervisor_backoff_base_s must be positive and "
                "<= supervisor_backoff_max_s")


@dataclass
class AdmissionConfig:
    """Overload-protection plane (resilience/admission.py,
    docs/RESILIENCE.md overload rows): per-tenant token-bucket quotas per
    request class, the weighted-fair search queue, edge-minted deadlines,
    capacity-aware generation admission, and the SLO shed ladder. Tenant
    identity comes from the `X-Symbiont-Tenant` HTTP header (default
    tenant otherwise); quotas are PER TENANT, so one hot tenant is clamped
    to its own budget instead of starving everyone."""

    enabled: bool = True
    # per-tenant token buckets: sustained requests/second + burst headroom,
    # one bucket per (tenant, class). Exhaustion answers 429 with
    # Retry-After at the HTTP edge — the queue never grows unboundedly.
    ingest_rate: float = 200.0
    ingest_burst: float = 400.0
    search_rate: float = 100.0
    search_burst: float = 200.0
    generate_rate: float = 20.0
    generate_burst: float = 40.0
    # weighted-fair search scheduling: shared concurrency budget, bounded
    # per-tenant wait queues (full queue → 429), stride weights like
    # "gold=4,free=1" (unlisted tenants weigh 1)
    search_concurrency: int = 32
    max_queue_per_tenant: int = 64
    fair_weights: str = ""
    # distinct tenant identities the edge will track: the tenant header is
    # client-supplied, so past this bound every NEW identity shares one
    # overflow bucket/queue (quota-bypass-by-fresh-tenant and unbounded
    # per-tenant state/metric cardinality both stop here)
    max_tenants: int = 1024
    # deadlines minted at the API edge (X-Symbiont-Deadline, absolute epoch
    # ms), threaded through every bus hop by telemetry.child_headers;
    # expired work is dropped before the handler runs (never retried,
    # never DLQ'd). 0 disables minting for that class; a client-supplied
    # deadline always passes through (and can only TIGHTEN a minted one).
    # INGEST defaults to NO minted deadline: the edge already answered 200
    # "submitted successfully", and an expiring deadline would silently
    # drop accepted data during a redelivery storm — violating the plane's
    # own ingest-is-never-shed / zero-loss invariant. Opt in only if your
    # clients treat submit-url as best-effort.
    deadline_ingest_ms: float = 0.0
    deadline_search_ms: float = 10000.0
    deadline_generate_ms: float = 60000.0
    # capacity-aware generation admission: refuse new generation streams
    # (429) once the LM's allocated KV rows across live decode sessions
    # reach this bound (LmEngine.can_admit); 0 = unbounded (the pre-plane
    # behavior)
    max_kv_rows: int = 0
    # shed-ladder hysteresis (resilience/admission.DegradationLadder):
    # dwell time between level changes and consecutive breach-free
    # watchdog passes required to step down — an oscillating breach parks
    # the ladder instead of flapping it
    shed_recovery_passes: int = 3
    shed_hold_s: float = 5.0
    # degraded-search rung: top-k clamp (rerank is skipped outright)
    degraded_top_k: int = 3

    def __post_init__(self) -> None:
        for name in ("ingest", "search", "generate"):
            if (getattr(self, f"{name}_rate") <= 0
                    or getattr(self, f"{name}_burst") <= 0):
                raise ValueError(
                    f"admission.{name}_rate/_burst must be positive")
        if self.search_concurrency < 1 or self.max_queue_per_tenant < 1:
            raise ValueError(
                "admission.search_concurrency and max_queue_per_tenant "
                "must be >= 1")
        if self.max_tenants < 1:
            raise ValueError("admission.max_tenants must be >= 1")
        if self.shed_recovery_passes < 1:
            raise ValueError("admission.shed_recovery_passes must be >= 1")
        if self.shed_hold_s < 0:
            raise ValueError("admission.shed_hold_s must be >= 0")
        if self.degraded_top_k < 1:
            raise ValueError("admission.degraded_top_k must be >= 1")
        if self.max_kv_rows < 0:
            raise ValueError("admission.max_kv_rows must be >= 0")
        # malformed weights fail at boot, not silently weight 1
        from symbiont_tpu.resilience.admission import parse_weights

        parse_weights(self.fair_weights)


@dataclass
class AutoscaleConfig:
    """SLO-driven elastic autoscaling (resilience/autoscale.py,
    docs/RESILIENCE.md "Elastic autoscaling"): the ProcessSupervisor's
    policy engine that grows and shrinks role-split fleets from the
    pressure signals the admission plane and fleet telemetry already
    measure. Off by default — a fixed-size deployment behaves exactly as
    before. Scale-in always retires through the drain protocol (the
    worker detaches its durable consumers, flushes its coalescer,
    finishes in-flight work, beats `draining: true`, and exits), with
    `drain_deadline_s` + SIGKILL + durable redelivery as the safety net."""

    enabled: bool = False
    # elastic roles and their replica bounds: "embed=1:4,decode=1:2".
    # Every listed role must exist as a supervised worker; the base
    # replica (index 1) is never retired, so min >= 1.
    roles: str = ""
    # seconds between policy evaluations
    eval_s: float = 2.0
    # scale-out pressure: per-replica engine queue depth (the federated
    # `batcher.queue_depth` + `batcher.tenant_depth` gauges) above
    # queue_high is full pressure; below queue_low counts as a clean
    # (scale-in-eligible) pass
    queue_high: float = 64.0
    queue_low: float = 4.0
    # KV-occupancy pressure for decode roles: allocated KV rows
    # (`lm.kv_rows_allocated`) above this is full pressure; 0 disables
    kv_high_rows: float = 0.0
    # breaker-style hysteresis (the DegradationLadder shape): a scale-out
    # needs out_dwell_s since the role's last change; a scale-in needs
    # in_clean_passes CONSECUTIVE low-pressure evaluations AND
    # in_dwell_s — a flapping signal parks the fleet at its size instead
    # of thrashing spawn/drain cycles
    out_dwell_s: float = 10.0
    in_dwell_s: float = 60.0
    in_clean_passes: int = 5
    # global scale budget: at most budget_ops scale operations (out or
    # in, all roles together) per budget_window_s — a runaway signal or
    # crash-looping role cannot thrash the box
    budget_ops: int = 6
    budget_window_s: float = 300.0
    # drain enforcement: a retiring worker that has not exited this many
    # seconds after the drain request is SIGKILLed (its unacked durable
    # deliveries redeliver to the surviving replicas — zero loss either
    # way)
    drain_deadline_s: float = 30.0

    def __post_init__(self) -> None:
        for name in ("eval_s", "out_dwell_s", "budget_window_s",
                     "drain_deadline_s"):
            if getattr(self, name) <= 0:
                raise ValueError(f"autoscale.{name} must be positive")
        if self.in_dwell_s < 0 or self.kv_high_rows < 0:
            raise ValueError(
                "autoscale.in_dwell_s and kv_high_rows must be >= 0")
        if self.queue_high <= 0 or self.queue_low < 0 \
                or self.queue_low >= self.queue_high:
            raise ValueError(
                "autoscale.queue_low must be >= 0 and < queue_high")
        if self.in_clean_passes < 1 or self.budget_ops < 1:
            raise ValueError(
                "autoscale.in_clean_passes and budget_ops must be >= 1")
        # malformed role bounds fail at boot, not silently never scale
        from symbiont_tpu.resilience.autoscale import parse_role_bounds

        parse_role_bounds(self.roles)


@dataclass
class GenJournalConfig:
    """Generation-session durability plane (docs/RESILIENCE.md "Durable
    generation sessions"): a per-role write-ahead journal of in-flight
    decode state, appended at the stream's existing chunk-boundary host
    syncs. When a generator worker dies mid-stream (SIGKILL, hang verdict,
    drain deadline) the supervisor republishes the journal tails as
    tasks.generation.resume, and a surviving replica continues the stream
    token-identically (greedy; sampled streams restore the journaled PRNG
    state). Off by default: journaling is a per-deployment durability
    opt-in, not a hot-path tax."""

    enabled: bool = False
    # journal directory; each role writes `<dir>/<role>.genlog` (JSONL, one
    # self-contained snapshot per chunk — the last record per task is the
    # full resume state)
    dir: str = "data/genlog"
    # compaction threshold: past this many bytes the file is rewritten
    # keeping only live tasks' tail records
    max_bytes: int = 8 * 1024 * 1024
    # live-task bound: oldest tasks are evicted (counted) past this — a
    # leak in done-marking cannot grow the journal without limit
    max_tasks: int = 512
    # fsync every append. Durability vs throughput: the default rides the
    # OS page cache (survives process SIGKILL, the failure mode this plane
    # targets; not a host power cut)
    fsync: bool = False
    # resume-under-pressure: a resume refused by admission (PoolExhausted /
    # can_admit false) re-queues with exponential backoff up to this many
    # attempts before it is abandoned (counted gen.resume_abandoned)
    resume_max_attempts: int = 5
    resume_backoff_s: float = 0.25

    def __post_init__(self) -> None:
        if self.max_bytes < 4096:
            raise ValueError("gen_journal.max_bytes must be >= 4096")
        if self.max_tasks < 1:
            raise ValueError("gen_journal.max_tasks must be >= 1")
        if self.resume_max_attempts < 0 or self.resume_backoff_s < 0:
            raise ValueError("gen_journal.resume_max_attempts and "
                             "resume_backoff_s must be >= 0")


@dataclass
class RunnerConfig:
    """Which services this process hosts (SYMBIONT_RUNNER_SERVICES).

    "all", or a comma list among: perception, preprocessing, vector_memory,
    knowledge_graph, text_generator, api, engine. "engine" is the engine.*
    request-reply plane (services/engine_service.py) that the native C++
    worker shells call into — a deployment of native workers runs a Python
    process with just `engine` plus the C++ binaries against the broker.
    """

    services: str = "all"
    # process-failure plane (resilience/procsup.py): when heartbeat_s > 0
    # the stack publishes a liveness heartbeat to `_sys.heartbeat.<role>`
    # every heartbeat_s seconds — the signal the process supervisor uses to
    # detect a HUNG (SIGSTOPped, deadlocked) worker that an exit code can't
    # reveal. `role` names this process in heartbeats and procsup metrics;
    # empty = derived from the services list.
    role: str = ""
    heartbeat_s: float = 0.0

    def __post_init__(self) -> None:
        if self.heartbeat_s < 0:
            raise ValueError("runner.heartbeat_s must be >= 0")


@dataclass
class SymbiontConfig:
    bus: BusConfig = field(default_factory=BusConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    lm: LmConfig = field(default_factory=LmConfig)
    vector_store: VectorStoreConfig = field(default_factory=VectorStoreConfig)
    graph_store: GraphStoreConfig = field(default_factory=GraphStoreConfig)
    api: ApiConfig = field(default_factory=ApiConfig)
    text_generator: TextGeneratorConfig = field(
        default_factory=TextGeneratorConfig)
    perception: PerceptionConfig = field(default_factory=PerceptionConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    runner: RunnerConfig = field(default_factory=RunnerConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    autoscale: AutoscaleConfig = field(default_factory=AutoscaleConfig)
    gen_journal: GenJournalConfig = field(default_factory=GenJournalConfig)

    def __post_init__(self) -> None:
        # cross-section invariant: every top_k the gateway routes to the
        # fused path must land in a pre-warmed k bucket, or the first such
        # query pays a cold XLA compile inside the probe timeout and trips
        # the negative cache for everyone. Fail at startup, not in that
        # degraded 60s window. (The standalone C++ gateway reads
        # SYMBIONT_API_FUSED_SEARCH_MAX_TOP_K with the same default; keep
        # them in lockstep in deployment env.)
        if self.api.fused_search_max_top_k > self.vector_store.warm_top_k:
            raise ValueError(
                f"api.fused_search_max_top_k ({self.api.fused_search_max_top_k})"
                f" must be <= vector_store.warm_top_k "
                f"({self.vector_store.warm_top_k}): fused queries above the "
                f"warmed k buckets would compile cold inside the probe timeout")


# Reference-era env aliases → (section, field) (reference: .env.example:1-12).
_ENV_ALIASES = {
    "NATS_URL": ("bus", "url"),
    "QDRANT_URI": ("vector_store", "uri"),
    "NEO4J_URI": ("graph_store", "uri"),
    "NEO4J_USER": ("graph_store", "user"),
    "NEO4J_PASSWORD": ("graph_store", "password"),
    "API_SERVER_HOST": ("api", "host"),
    "API_SERVER_PORT": ("api", "port"),
    "FORCE_CPU": ("engine", "force_cpu"),
    "EMBEDDING_MODEL_NAME": ("engine", "model_name"),
}


def _coerce(tp: Any, raw: str) -> Any:
    if tp is bool or tp == Optional[bool]:
        return raw.lower() in ("1", "true", "yes", "on")
    if tp is int or tp == Optional[int]:
        return int(raw)
    if tp is float or tp == Optional[float]:
        return float(raw)
    if tp in (List[int], List[str], List[float], Optional[List[int]]):
        parsed = json.loads(raw)
        return parsed
    return raw


def _apply_overrides(cfg: SymbiontConfig, env: dict[str, str]) -> None:
    import typing

    hints_by_section = {
        f.name: typing.get_type_hints(type(getattr(cfg, f.name)))
        for f in dataclasses.fields(cfg)
    }
    # Legacy reference-era aliases apply FIRST so canonical SYMBIONT_* vars win
    # when both are set.
    for alias, (sec, fld) in _ENV_ALIASES.items():
        if alias in env:
            setattr(getattr(cfg, sec), fld, _coerce(hints_by_section[sec][fld], env[alias]))
    for section_field in dataclasses.fields(cfg):
        section = getattr(cfg, section_field.name)
        hints = hints_by_section[section_field.name]
        for f in dataclasses.fields(section):
            key = f"SYMBIONT_{section_field.name.upper()}_{f.name.upper()}"
            if key in env:
                setattr(section, f.name, _coerce(hints[f.name], env[key]))


def _check_type(key: str, tp: Any, v: Any) -> Any:
    """Validate a config-file value against the field's declared type."""
    import typing

    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        if v is None:
            return None
        inner = [a for a in typing.get_args(tp) if a is not type(None)][0]
        return _check_type(key, inner, v)
    if origin is list:
        if not isinstance(v, list):
            raise ValueError(f"config key {key!r}: expected list, got {type(v).__name__}")
        (elem,) = typing.get_args(tp)
        return [_check_type(key, elem, x) for x in v]
    if tp is float:
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(f"config key {key!r}: expected number, got {type(v).__name__}")
        return float(v)
    if tp in (int, str, bool):
        if not isinstance(v, tp) or (tp is int and isinstance(v, bool)):
            raise ValueError(
                f"config key {key!r}: expected {tp.__name__}, got {type(v).__name__}")
        return v
    return v


def _merge_dict(cfg_obj: Any, data: dict) -> None:
    import typing

    hints = typing.get_type_hints(type(cfg_obj))
    for k, v in data.items():
        if not hasattr(cfg_obj, k):
            raise ValueError(f"unknown config key {k!r} for {type(cfg_obj).__name__}")
        cur = getattr(cfg_obj, k)
        if dataclasses.is_dataclass(cur) and isinstance(v, dict):
            _merge_dict(cur, v)
        else:
            setattr(cfg_obj, k, _check_type(k, hints[k], v))


def load_config(
    path: str | Path | None = None, env: dict[str, str] | None = None
) -> SymbiontConfig:
    """defaults < json config file < env vars (legacy aliases below SYMBIONT_*)."""
    cfg = SymbiontConfig()
    env_map = os.environ if env is None else env
    explicit = path is not None
    if path is None:
        path = env_map.get("SYMBIONT_CONFIG")
    if path is not None:
        if Path(path).exists():
            _merge_dict(cfg, json.loads(Path(path).read_text()))
        elif explicit:
            raise FileNotFoundError(f"config file not found: {path}")
    _apply_overrides(cfg, env_map)
    _validate(cfg)
    return cfg


def _validate(cfg: SymbiontConfig) -> None:
    """Re-run every dataclass __post_init__ validator AFTER file/env
    overrides: _merge_dict/_apply_overrides mutate the already-constructed
    sections via setattr, which bypasses dataclass construction — without
    this, the validators only ever see defaults."""
    for section_field in dataclasses.fields(cfg):
        section = getattr(cfg, section_field.name)
        post = getattr(section, "__post_init__", None)
        if post is not None:
            post()
    cfg.__post_init__()
