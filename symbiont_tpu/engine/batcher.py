"""Async micro-batching queue in front of the engine.

SURVEY.md §7 hard-part #1/#4: the bus delivers one document/query at a time,
the TPU wants large uniform batches, and the interactive search path (p50
latency) must not wait behind bulk ingest. Two policies over one engine:

- `MicroBatcher` — aggregates submissions; flushes when `max_batch` items are
  queued or the oldest item has waited `flush_deadline_ms`. Queries ride in
  the next flush (small batch, low latency); bulk ingest fills batches.
- Ingest callers submit whole documents (many sentences at once) and get all
  vectors back in one future.

The reference's model — spawn a task per message, all contending on one model
(reference: services/preprocessing_service/src/main.rs:376,425) — is exactly
what this replaces (SURVEY.md §5.2 hazard).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from symbiont_tpu.engine.engine import TpuEngine

log = logging.getLogger(__name__)


@dataclass
class _Pending:
    texts: List[str]
    future: asyncio.Future


class MicroBatcher:
    def __init__(self, engine: TpuEngine, max_batch: Optional[int] = None,
                 flush_deadline_ms: Optional[float] = None):
        self.engine = engine
        self.max_batch = max_batch or engine.config.max_batch
        self.deadline_s = (flush_deadline_ms
                           if flush_deadline_ms is not None
                           else engine.config.flush_deadline_ms) / 1000.0
        self._queue: List[_Pending] = []
        self._queued_texts = 0
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._closed = False

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(), name="micro-batcher")

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._task is not None:
            await self._task
            self._task = None

    async def embed(self, texts: Sequence[str]) -> np.ndarray:
        """Submit texts; resolves with [n, dim] when their batch flushes."""
        if self._closed:
            raise RuntimeError("batcher closed")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue.append(_Pending(list(texts), fut))
        self._queued_texts += len(texts)
        self._wake.set()
        return await fut

    async def _run(self) -> None:
        while True:
            if not self._queue:
                if self._closed:
                    return
                self._wake.clear()
                await self._wake.wait()
                continue
            if self._queued_texts < self.max_batch and not self._closed:
                # deadline flush: give late arrivals a short window to batch up
                try:
                    await asyncio.wait_for(self._sleep_until_full(), self.deadline_s)
                except asyncio.TimeoutError:
                    pass
            batch, self._queue = self._queue, []
            self._queued_texts = 0
            texts: List[str] = []
            for p in batch:
                texts.extend(p.texts)
            try:
                # off the event loop: the forward is CPU/TPU-bound
                vecs = await asyncio.get_running_loop().run_in_executor(
                    None, self.engine.embed_texts, texts)
                offset = 0
                for p in batch:
                    n = len(p.texts)
                    if not p.future.cancelled():
                        p.future.set_result(vecs[offset:offset + n])
                    offset += n
            except Exception as e:  # propagate to every waiter
                log.exception("batch embed failed")
                for p in batch:
                    if not p.future.cancelled():
                        p.future.set_exception(e)

    async def _sleep_until_full(self) -> None:
        while self._queued_texts < self.max_batch and not self._closed:
            self._wake.clear()
            await self._wake.wait()
