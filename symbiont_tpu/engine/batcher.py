"""Async micro-batching queues in front of the engine.

SURVEY.md §7 hard-part #1/#4: the bus delivers one document/query at a time,
the TPU wants large uniform batches, and the interactive search path (p50
latency) must not wait behind bulk ingest. Two policies over one engine:

- `MicroBatcher` (embedding) — aggregates submissions; flushes when
  `max_batch` items are queued or the oldest item has waited
  `flush_deadline_ms`. Queries ride in the next flush (small batch, low
  latency); bulk ingest fills batches.
- `GenBatcher` (generation) — same loop; concurrent tasks.generation.text
  requests within the flush window decode as ONE batched gpt.generate call
  instead of serializing on the engine lock, sharing every weight read of
  the decode loop. Requests group by new-token bucket.

Both share one flush loop (`_BatcherBase`): wake on submission, wait up to
the deadline for the batch to fill, then flush AT MOST max_batch items —
a backlog drains in max_batch-sized chunks, never as one giant device call.

The reference's model — spawn a task per message, all contending on one model
(reference: services/preprocessing_service/src/main.rs:376,425) — is exactly
what this replaces (SURVEY.md §5.2 hazard).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.obs.engine_timeline import engine_timeline
from symbiont_tpu.obs.usage import usage
from symbiont_tpu.resilience.admission import (
    DEFAULT_TENANT,
    OVERFLOW_TENANT,
    AdmissionReject,
    StrideClock,
)
from symbiont_tpu.utils.telemetry import metrics

log = logging.getLogger(__name__)

# distinct tenant lanes a batcher keeps before folding NEW identities into
# the shared overflow lane — same bounded-universe stance as the edge's
# admission.max_tenants (the tenant header is client-supplied)
MAX_TENANT_LANES = 256

# interactive lane class: a tenant's single query embed must never FIFO
# behind that SAME tenant's hundreds-deep bulk-ingest lane — measured by
# the load_ramp tier (4x ingest ramp: same-tenant query embeds waited out
# the whole backlog, 10s bus timeouts) — so interactive work rides
# "<tenant>#q", which the stride clock interleaves fairly against the
# tenant's bulk lane. At most 2x the lane cardinality, still bounded by
# MAX_TENANT_LANES.
INTERACTIVE_LANE_SUFFIX = "#q"


def interactive_lane(tenant: str) -> str:
    """The fairness-lane identity for one tenant's INTERACTIVE work."""
    return f"{tenant}{INTERACTIVE_LANE_SUFFIX}"


class TenantLanes:
    """Per-tenant FIFO lanes drained in stride-fair order (engine-plane
    fairness, ROADMAP item 5 remainder).

    The PR 9 overload plane enforced tenant fairness only at the API edge;
    the micro-batcher itself was one FIFO deque — so any path that bypasses
    the edge (a replicated gateway without admission, a native shell calling
    engine.* directly, a restarted worker draining a durable backlog)
    re-created hot-tenant starvation at the device queue. These lanes move
    the guarantee into the batcher: each queued item lands in its tenant's
    bounded lane, and the drain order is stride scheduling over the SAME
    `StrideClock` the edge fair queue runs (resilience/admission.py) — a
    tenant with 80 queued embeds interleaves 1:1 with a tenant holding 2,
    instead of serializing ahead of it.

    Single-tenant behavior is exactly the old FIFO deque (one lane), so
    every pre-existing ordering contract holds unchanged. A full lane
    rejects (`AdmissionReject` → typed engine error / handler failure whose
    durable delivery redelivers later) — bounded memory, never unbounded
    queue growth behind the device.

    Duck-typing: supports the deque surface the batcher (and its tests)
    use — `len`, truthiness, iteration in drain order (non-mutating),
    `clear()` — plus the fair `append/peek/popleft/requeue_front/drain`
    cycle. Items without a `.tenant` attribute ride the default lane.
    """

    def __init__(self, kind: str = "batcher", max_per_tenant: int = 0,
                 max_lanes: int = MAX_TENANT_LANES,
                 weights: Optional[dict] = None):
        self.kind = kind
        self.max_per_tenant = int(max_per_tenant)
        self.max_lanes = int(max_lanes)
        self._clock = StrideClock(weights)
        self._lanes: "dict[str, deque]" = {}
        # CUMULATIVE identity bound (the edge's resolve_tenant stance): the
        # tenant header is client-supplied, so bounding only the CONCURRENT
        # lane count would still let a client cycling fresh identities one
        # request at a time grow clock state and the tenant_depth gauge
        # label space without limit — past max_lanes identities ever seen,
        # every NEW name shares the overflow lane.
        self._seen: set = {DEFAULT_TENANT}
        self._n = 0

    # ------------------------------------------------------------- plumbing

    def _lane_key(self, item) -> str:
        tenant = getattr(item, "tenant", None) or DEFAULT_TENANT
        if tenant in self._seen or tenant in self._clock.weights:
            return tenant
        if len(self._seen) >= self.max_lanes:
            return OVERFLOW_TENANT
        self._seen.add(tenant)
        return tenant

    def _gauge(self, tenant: str) -> None:
        metrics.gauge_set("batcher.tenant_depth",
                          len(self._lanes.get(tenant, ())),
                          labels={"batcher": self.kind, "tenant": tenant})

    def _drop_if_empty(self, tenant: str) -> None:
        lane = self._lanes.get(tenant)
        if lane is not None and not lane:
            del self._lanes[tenant]
            # no banked lateness is erased: the clock only forgets a tenant
            # whose virtual time is at/below the floor
            self._clock.forget(tenant)

    # ------------------------------------------------------------------ api

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def __iter__(self):
        return iter(self.fair_order())

    def fair_order(self) -> List:
        """Every queued item in the order popleft() would serve them —
        computed on snapshots, nothing consumed."""
        clock = self._clock.snapshot()
        lanes = {t: list(q) for t, q in self._lanes.items() if q}
        out: List = []
        while lanes:
            tenant = clock.pick(lanes)
            lane = lanes[tenant]
            out.append(lane.pop(0))
            clock.charge(tenant)
            if not lane:
                del lanes[tenant]
        return out

    def append(self, item) -> None:
        tenant = self._lane_key(item)
        lane = self._lanes.setdefault(tenant, deque())
        if self.max_per_tenant and len(lane) >= self.max_per_tenant:
            self._drop_if_empty(tenant)
            metrics.inc("batcher.lane_rejected",
                        labels={"batcher": self.kind, "tenant": tenant})
            raise AdmissionReject(
                "engine_lane_full", retry_after_s=1.0,
                message=f"tenant {tenant!r} {self.kind} lane is full "
                        f"({self.max_per_tenant} queued at the engine)")
        lane.append(item)
        self._n += 1
        self._gauge(tenant)

    def peek(self):
        """The item the next popleft() will return (deterministic between
        mutations); None when empty."""
        tenant = self._clock.pick(t for t, q in self._lanes.items() if q)
        return None if tenant is None else self._lanes[tenant][0]

    def popleft(self):
        tenant = self._clock.pick(t for t, q in self._lanes.items() if q)
        if tenant is None:
            raise IndexError("pop from empty TenantLanes")
        item = self._lanes[tenant].popleft()
        self._clock.charge(tenant)
        self._n -= 1
        self._gauge(tenant)
        self._drop_if_empty(tenant)
        return item

    def requeue_front(self, items: List) -> None:
        """Stolen-but-unserved items go back to the FRONT of their own
        lanes in original arrival order — the cross-lane drain order is the
        clock's business, per-lane FIFO is preserved."""
        per_lane: "dict[str, List]" = {}
        for item in items:
            per_lane.setdefault(self._lane_key(item), []).append(item)
        for tenant, block in per_lane.items():
            lane = self._lanes.setdefault(tenant, deque())
            # extendleft reverses its argument, so reversed() lands the
            # block at the front IN ORIGINAL ORDER (pinned by tests)
            lane.extendleft(reversed(block))
            self._n += len(block)
            self._gauge(tenant)

    def drain_fair(self) -> List:
        """Pop everything in fair order (the GenBatcher steal)."""
        out: List = []
        while self._n:
            out.append(self.popleft())
        return out

    def clear(self) -> None:
        for tenant, lane in list(self._lanes.items()):
            lane.clear()
            self._gauge(tenant)
            self._drop_if_empty(tenant)
        self._n = 0

    def oldest_submit(self) -> Optional[float]:
        """Earliest _t_submit across lane heads (each lane is FIFO, so its
        head is its oldest) — feeds the queue-age gauge."""
        heads = [q[0] for q in self._lanes.values() if q]
        times = [getattr(h, "_t_submit", None) for h in heads]
        times = [t for t in times if t is not None]
        return min(times) if times else None


class _BatcherBase:
    """Queue + wake + deadline-flush loop shared by the embed and generation
    batchers. Subclasses define `_size(item)` (how much of max_batch an item
    consumes) and `_flush(batch)` (resolve every item's future).

    `max_inflight_flushes` > 1 lets the loop start flush N+1 while flush N's
    results are still materializing — on a network-attached device a flush
    tail is ~an RTT of pure waiting, so overlapping flushes keeps the chip
    fed (the engine's entry points are thread-safe by design; see
    engine.py's concurrency contract): batch N+1 tokenizes/pads/dispatches
    on its own executor thread while batch N's forward runs. Generation
    keeps it at 1: decode sessions admit newcomers at chunk boundaries
    instead, and two sessions would only contend on the LM lock.

    Result-order contract under overlap: each submission's future is bound
    to its exact slice of its OWN flush, so flush N+1 completing before
    flush N (a short batch overtaking a long one) resolves the later
    submitters first but can never mis-route rows — per-submission results
    are positionally exact regardless of flush completion order (pinned by
    tests/test_coalesce.py's slow-forward ordering test).

    Live accounting for the double-buffering (engine-plane gauges):
    `batcher.inflight` is the flush count currently in the air and
    `batcher.overlap_ratio` is the fraction of cumulative flush seconds
    that ran concurrently with another flush — 0.0 means lockstep (no
    overlap won), approaching 1-1/k means the window of k stayed full."""

    # metric label distinguishing the two policies over one registry
    kind = "batcher"

    def __init__(self, max_batch: int, deadline_s: float,
                 max_inflight_flushes: int = 1, lane_depth: int = 0):
        self.max_batch = max_batch
        self.deadline_s = deadline_s
        # per-tenant bounded lanes drained stride-fair (TenantLanes): the
        # single-tenant case degenerates to the old FIFO deque; under a
        # multi-tenant backlog the chunk composition interleaves tenants so
        # an edge-bypassing hot tenant cannot starve the rest at the device
        self._queue: TenantLanes = TenantLanes(kind=self.kind,
                                               max_per_tenant=lane_depth)
        self._queued = 0
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self._inflight = asyncio.Semaphore(max_inflight_flushes)
        self._flushes: set = set()
        # overlap accounting (all touched on the event-loop thread only):
        # span = Σ individual flush durations; busy = wall seconds with ≥1
        # flush in flight. span - busy is flush time that OVERLAPPED another
        # flush — overlap_ratio = 1 - busy/span.
        self._inflight_n = 0
        self._busy_since = 0.0
        self._flush_busy_s = 0.0
        self._flush_span_s = 0.0

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(
                self._run(), name=type(self).__name__)
            self._register_gauges()

    def _register_gauges(self) -> None:
        """Engine-plane queue gauges, read at scrape time. Weakref-bound
        (register_weakref_gauge): a dead or closed batcher's gauges retire
        themselves — tests churn through batchers and the registry must not
        pin them."""
        labels = {"service": "engine", "batcher": self.kind}

        def depth(b):
            return None if b._closed else b._queued

        def oldest_wait_s(b):
            if b._closed:
                return None
            if not b._queue:
                return 0.0
            # per-lane FIFO (requeues go to the FRONT), so the oldest item
            # is the earliest lane head
            t = b._queue.oldest_submit()
            return 0.0 if t is None else max(0.0, time.monotonic() - t)

        def inflight(b):
            return None if b._closed else b._inflight_n

        def overlap_ratio(b):
            if b._closed:
                return None
            span = b._flush_span_s
            if span <= 0.0:
                return 0.0
            return round(max(0.0, 1.0 - b._flush_busy_s / span), 4)

        metrics.register_weakref_gauge("batcher.queue_depth", self, depth,
                                       labels=labels)
        metrics.register_weakref_gauge("batcher.oldest_wait_s", self,
                                       oldest_wait_s, labels=labels)
        metrics.register_weakref_gauge("batcher.inflight", self, inflight,
                                       labels=labels)
        metrics.register_weakref_gauge("batcher.overlap_ratio", self,
                                       overlap_ratio, labels=labels)

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._task is not None:
            await self._task
            self._task = None
        if self._flushes:
            await asyncio.gather(*self._flushes, return_exceptions=True)
        # a flush can re-queue items (splice rejection, unadmittable keep)
        # after _run has already exited — with no loop left to serve them,
        # their futures would hang forever. All flushes are done now, so the
        # queue is final: fail what's left.
        leftovers = self._queue.drain_fair()
        self._queued = 0
        for item in leftovers:
            if not item.future.done():
                item.future.set_exception(RuntimeError("batcher closed"))

    def _submit(self, item) -> None:
        if self._closed:
            raise RuntimeError("batcher closed")
        item._t_submit = time.monotonic()  # queue-age gauge reads this
        self._queue.append(item)
        self._queued += self._size(item)
        self._wake.set()

    def _requeue(self, items: List) -> None:
        """Put stolen-but-unserved items back, ahead of anything submitted
        meanwhile (preserve per-lane arrival order), and wake the run loop —
        it may have parked on a cleared _wake after the steal emptied the
        queue; without a wake the re-queued items sit unserved until an
        unrelated submission arrives (ADVICE r4 medium)."""
        if not items:
            return
        self._queue.requeue_front(items)
        self._queued += sum(self._size(k) for k in items)
        self._wake.set()

    def _take_chunk(self) -> List:
        """Pop up to max_batch's worth of items (always at least one),
        composed across tenant lanes in stride-fair order."""
        taken: List = []
        size = 0
        while self._queue and (not taken
                               or size + self._size(self._queue.peek()) <= self.max_batch):
            item = self._queue.popleft()
            size += self._size(item)
            taken.append(item)
        self._queued -= size
        if taken:
            labels = {"service": "engine", "batcher": self.kind}
            fill = size / self.max_batch if self.max_batch else 0.0
            metrics.observe("batcher.flush_fill_ratio", fill, labels=labels)
            metrics.gauge_set("batcher.last_flush_fill_ratio", round(fill, 4),
                              labels=labels)
            # decode-plane flight recorder: queue depth AFTER the take —
            # the backlog a flush boundary leaves behind, on the same time
            # axis as the step/flush counters (obs/engine_timeline.py)
            engine_timeline.note_queue_depth(self.kind, self._queued)
        return taken

    async def _run(self) -> None:
        while True:
            if not self._queue:
                if self._closed:
                    return
                self._wake.clear()
                await self._wake.wait()
                continue
            if self._queued < self.max_batch and not self._closed:
                # deadline flush: give late arrivals a short window to batch up
                try:
                    await asyncio.wait_for(self._sleep_until_full(),
                                           self.deadline_s)
                except asyncio.TimeoutError:
                    pass
            await self._inflight.acquire()
            chunk = self._take_chunk()
            if not chunk:
                # an in-flight session's chunk-boundary admission can drain
                # the queue while we waited on the semaphore
                self._inflight.release()
                continue
            t = asyncio.create_task(self._flush_release(chunk))
            self._flushes.add(t)
            t.add_done_callback(self._flushes.discard)

    async def _flush_release(self, batch: List) -> None:
        t0 = time.monotonic()
        self._inflight_n += 1
        if self._inflight_n == 1:
            self._busy_since = t0
        try:
            await self._flush(batch)
        finally:
            self._inflight.release()
            t1 = time.monotonic()
            self._flush_span_s += t1 - t0
            self._inflight_n -= 1
            if self._inflight_n == 0:
                self._flush_busy_s += t1 - self._busy_since

    async def _sleep_until_full(self) -> None:
        while self._queued < self.max_batch and not self._closed:
            self._wake.clear()
            await self._wake.wait()

    # subclass interface -----------------------------------------------------

    def _size(self, item) -> int:
        raise NotImplementedError

    async def _flush(self, batch: List) -> None:
        raise NotImplementedError


@dataclass
class _Pending:
    texts: List[str]
    future: asyncio.Future
    # engine-plane fairness: the lane this item queues in (bus-header tenant
    # threaded down by the calling service; default lane otherwise)
    tenant: str = DEFAULT_TENANT


class MicroBatcher(_BatcherBase):
    kind = "embed"

    def __init__(self, engine: TpuEngine, max_batch: Optional[int] = None,
                 flush_deadline_ms: Optional[float] = None,
                 max_inflight_flushes: Optional[int] = None,
                 lane_depth: Optional[int] = None):
        deadline = (flush_deadline_ms if flush_deadline_ms is not None
                    else engine.config.flush_deadline_ms) / 1000.0
        from symbiont_tpu.config import EngineConfig

        mb = max_batch or engine.config.max_batch
        # mesh-aware flush sizing (docs/SCALING.md): round the flush cap up
        # to a multiple of the mesh 'data' axis so a full flush splits into
        # EVEN replica shards — a cap of, say, 100 over 8 replicas would
        # batch-bucket to 104 and ship 4 permanent pad rows per full flush.
        # Stub engines without DP accounting (tests) default to 1.
        nd = getattr(engine, "_n_data", 1)
        if nd > 1:
            mb = ((mb + nd - 1) // nd) * nd
        super().__init__(mb, deadline,
                         max_inflight_flushes=(
                             max_inflight_flushes
                             if max_inflight_flushes is not None
                             # duck-typed test configs may predate the
                             # field; fall back to the REAL dataclass
                             # default so a future tuning there is never
                             # shadowed by a stale literal here
                             else getattr(
                                 engine.config, "max_inflight_flushes",
                                 EngineConfig.max_inflight_flushes)),
                         lane_depth=(
                             lane_depth if lane_depth is not None
                             else getattr(engine.config, "tenant_lane_depth",
                                          EngineConfig.tenant_lane_depth)))
        self.engine = engine

    async def embed(self, texts: Sequence[str],
                    tenant: Optional[str] = None) -> np.ndarray:
        """Submit texts; resolves with [n, dim] when their batch flushes.
        `tenant` picks the fairness lane (engine-plane fairness survives
        edge bypass — docs/RESILIENCE.md); None rides the default lane."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._submit(_Pending(list(texts), fut,
                              tenant=tenant or DEFAULT_TENANT))
        return await fut

    def _size(self, item: _Pending) -> int:
        return len(item.texts)

    @staticmethod
    def _usage_tenant(lane: str) -> str:
        """The BILLING identity behind a fairness lane: interactive lanes
        ('<tenant>#q') charge the tenant itself — the lane split is a
        scheduling detail, not a second customer."""
        if lane.endswith(INTERACTIVE_LANE_SUFFIX):
            return lane[: -len(INTERACTIVE_LANE_SUFFIX)] or DEFAULT_TENANT
        return lane

    async def _flush(self, batch: List) -> None:
        texts: List[str] = []
        for p in batch:
            texts.extend(p.texts)
            # usage ledger (obs/usage.py): embed rows billed per tenant at
            # the flush that carries them
            usage.note(self._usage_tenant(p.tenant), embed_rows=len(p.texts))
        try:
            # off the event loop: the forward is CPU/TPU-bound
            vecs = await asyncio.get_running_loop().run_in_executor(
                None, self.engine.embed_texts, texts)
            offset = 0
            for p in batch:
                n = len(p.texts)
                if not p.future.cancelled():
                    p.future.set_result(vecs[offset:offset + n])
                offset += n
        except Exception as e:  # propagate to every waiter
            log.exception("batch embed failed")
            for p in batch:
                if not p.future.cancelled():
                    p.future.set_exception(e)


@dataclass
class _PendingGen:
    prompt: str
    max_new: int
    temperature: float
    top_k: int
    future: asyncio.Future
    # cancellation signal (anything with .is_set(); e.g. asyncio.Event):
    # checked at every chunk boundary — a vanished SSE reader's request
    # frees its decode row mid-session instead of pinning it to budget
    # exhaustion. A cancelled request's future resolves to None.
    cancel: Optional[object] = None
    # fairness lane (see _Pending.tenant)
    tenant: str = DEFAULT_TENANT
    # originating task id: keys the row's durability snapshots in the
    # generation journal (resilience/genlog.py); None = not journaled
    task_id: Optional[str] = None

    def cancelled(self) -> bool:
        return self.cancel is not None and self.cancel.is_set()


class GenBatcher(_BatcherBase):
    """Continuous batching for autoregressive generation.

    Requests that arrive within one flush window start a decode SESSION
    together (LmEngine.start_session); the session decodes in chunks, and at
    every chunk boundary newly-queued requests JOIN the in-flight decode in
    free batch rows (row padding from the power-of-two bucket, or rows whose
    request already finished) — a request that misses the window no longer
    waits behind the whole decode (VERDICT r3 item 3). Per-request
    temperature/top_k ride as per-row traced vectors; requests group by
    new-token bucket; a newcomer is admitted when a slot is free, its budget
    fits the session's remaining steps, and its prompt fits the session's
    prompt bucket (LmEngine.BatchSession.can_admit) — otherwise it waits for
    the next session."""

    kind = "generate"

    def __init__(self, lm, max_batch: Optional[int] = None,
                 flush_deadline_ms: Optional[float] = None,
                 lane_depth: Optional[int] = None):
        from symbiont_tpu.config import LmConfig

        deadline = (flush_deadline_ms if flush_deadline_ms is not None
                    else lm.config.gen_flush_deadline_ms) / 1000.0
        super().__init__(max_batch or lm.config.gen_max_batch, deadline,
                         lane_depth=(
                             lane_depth if lane_depth is not None
                             else getattr(lm.config, "gen_tenant_lane_depth",
                                          LmConfig.gen_tenant_lane_depth)))
        self.lm = lm
        self.stats = {"sessions": 0, "admitted_midflight": 0}

    async def generate(self, prompt: str, max_new_tokens: int,
                       temperature: Optional[float] = None,
                       top_k: Optional[int] = None,
                       cancel: Optional[object] = None,
                       tenant: Optional[str] = None,
                       task_id: Optional[str] = None) -> Optional[str]:
        """Returns the generated text, or None when `cancel` (an object
        with .is_set(), e.g. asyncio.Event) was set mid-decode and the
        request's row was freed at a chunk boundary. `tenant` picks the
        fairness lane (default lane otherwise); `task_id` keys the row's
        crash-resume snapshots in the generation journal."""
        cfg = self.lm.config
        temperature = cfg.temperature if temperature is None else temperature
        top_k = cfg.top_k if top_k is None else top_k
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._submit(_PendingGen(prompt, int(max_new_tokens),
                                 float(temperature), int(top_k), fut,
                                 cancel=cancel,
                                 tenant=tenant or DEFAULT_TENANT,
                                 task_id=task_id))
        return await fut

    def _size(self, item: _PendingGen) -> int:
        return 1

    def _bucket(self, max_new: int) -> int:
        for b in self.lm.config.new_token_buckets:
            if max_new <= b:
                return b
        return self.lm.config.new_token_buckets[-1]

    async def _flush(self, batch: List) -> None:
        loop = asyncio.get_running_loop()
        groups: dict = {}
        for p in batch:
            groups.setdefault(self._bucket(p.max_new), []).append(p)
        for group in groups.values():
            # requests cancelled while they sat in the flush window never
            # enter a session at all — their futures resolve to None here
            still = [p for p in group if not p.cancelled()]
            for p in group:
                if p.cancelled() and not p.future.done():
                    p.future.set_result(None)
            if not still:
                continue
            group = still
            # every request that ever joins this session; on session failure
            # each unresolved future gets the exception (a silently dropped
            # future would hang its caller forever)
            participants: List = list(group)
            by_tag: dict = {}
            prep_fut = None  # in-flight prepare: (future, take-items)
            # requests this session can NEVER admit (prompt over its prompt
            # bucket, or budget over its monotonically-shrinking remaining
            # steps): parked here until the session ends instead of
            # re-queued, or every chunk boundary would re-steal and
            # re-tokenize them (can_admit encodes the full prompt)
            deferred: List = []
            try:
                sess = await loop.run_in_executor(
                    None, lambda g=group: self.lm.start_session(
                        [p.prompt for p in g], [p.max_new for p in g],
                        temperature=[p.temperature for p in g],
                        top_k=[p.top_k for p in g],
                        tenants=[p.tenant for p in g],
                        task_ids=[p.task_id for p in g]))
                self.stats["sessions"] += 1
                for tag, p in zip((r.tag for r in sess.rows if r is not None),
                                  group):
                    by_tag[tag] = p
                while True:
                    # 1) harvest a finished prepare: splice the prefilled
                    #    rows in at this chunk boundary (cheap merge). Block
                    #    on the prepare only when the session has nothing
                    #    left to decode — otherwise keep stepping.
                    if prep_fut is not None and (
                            prep_fut[0].done()
                            or (sess.done() and not by_tag)):
                        fut, take = prep_fut
                        prep_fut = None
                        try:
                            prep = await fut
                        except Exception as e:
                            # a failed prefill kills only the newcomers —
                            # the in-flight session rows keep decoding
                            log.exception("newcomer prefill failed")
                            for p in take:
                                if not p.future.done():
                                    p.future.set_exception(e)
                            prep = None
                        if prep is not None:
                            try:
                                tags = await loop.run_in_executor(
                                    None, sess.splice, prep)
                            except Exception as e:
                                # same stance as a failed prefill: kill the
                                # newcomers, keep the session rows decoding
                                # (their futures are not in participants, so
                                # the outer handler can't reach them)
                                log.exception("newcomer splice failed")
                                for p in take:
                                    if not p.future.done():
                                        p.future.set_exception(e)
                                tags = None
                            if tags is None:
                                continue
                            for tag, p in zip(tags, take):
                                if tag is None:
                                    # splice rejection is permanent for this
                                    # session too (budget vs remaining)
                                    deferred.append(p)
                                else:
                                    by_tag[tag] = p
                                    participants.append(p)
                                    self.stats["admitted_midflight"] += 1
                    # 1b) cancellation sweep at the chunk boundary: a
                    #     vanished client's row frees NOW (admissible to
                    #     newcomers, kv gauges drop it) instead of decoding
                    #     to budget exhaustion (BatchSession.cancel_tag)
                    swept = [(tag, p) for tag, p in by_tag.items()
                             if p.cancelled()]
                    if swept:
                        # cancel_tag takes the ENGINE lock, which an
                        # executor thread can hold through a decode chunk
                        # or a first-call XLA compile — never block the
                        # event loop on it
                        await loop.run_in_executor(
                            None,
                            lambda: [sess.cancel_tag(t) for t, _ in swept])
                    for tag, p in swept:
                        by_tag.pop(tag)
                        if not p.future.done():
                            p.future.set_result(None)
                        self.stats["cancelled"] = (
                            self.stats.get("cancelled", 0) + 1)
                    if sess.done() and not by_tag and prep_fut is None:
                        # prep_fut pending (e.g. the sweep just cancelled
                        # every row) must NOT be abandoned here: the next
                        # iteration's harvest force-awaits it — splicing
                        # its rows in if budget remains, failing/deferring
                        # them otherwise — so no newcomer future ever
                        # dangles off a normal session exit
                        break
                    # 2) steal the queue and start preparing newcomers —
                    #    overlapped with the step below, never awaited here
                    if (prep_fut is None and self._queue
                            and sess.capacity() > 0):
                        # steal in stride-fair order: admission slots fill
                        # across tenants, not first-come within one lane
                        candidates = self._queue.drain_fair()
                        self._queued -= sum(self._size(c) for c in candidates)
                        try:
                            take, retry, defer = await loop.run_in_executor(
                                None, self._filter_candidates, sess,
                                candidates)
                        except Exception as e:
                            # stolen items are in nobody's hands now — fail
                            # them or their callers hang forever
                            log.exception("admission filter failed")
                            for p in candidates:
                                if not p.future.done():
                                    p.future.set_exception(e)
                            take, retry, defer = [], [], []
                        # transiently rejected (batch full) go straight back:
                        # a row may free at the next chunk boundary and they
                        # must not wait out the whole session
                        self._requeue(retry)
                        deferred.extend(defer)
                        if take:
                            prep_fut = (loop.run_in_executor(
                                None, self._do_prepare, sess, take), take)
                    # 3) decode one chunk (the prepare, if any, is prefilling
                    #    on another executor thread meanwhile). Turnaround
                    #    includes the event-loop -> executor hop both ways:
                    #    subtracting the timeline's device wall for the same
                    #    chunk isolates the batcher's share of the host gap
                    #    that obs/xprof.py attributes per chunk.
                    t_hop = time.monotonic()
                    finished = await loop.run_in_executor(None, sess.step)
                    metrics.observe("batcher.step_turnaround_ms",
                                    (time.monotonic() - t_hop) * 1000.0,
                                    labels={"service": "lm"})
                    for tag, text in finished:
                        p = by_tag.pop(tag)
                        if not p.future.cancelled():
                            p.future.set_result(text)
            except Exception as e:
                log.exception("batch generate session failed")
                if prep_fut is not None:
                    prep_fut[0].cancel()
                    participants.extend(prep_fut[1])
                for p in participants:
                    if not p.future.done():
                        p.future.set_exception(e)
            finally:
                # deferred items never joined this session — hand them to
                # the next one (front of queue: preserve arrival order)
                self._requeue(deferred)

    def _filter_candidates(self, sess, candidates: List):
        """Executor-side: split candidates into (take, keep). can_admit
        tokenizes, so it runs off the loop. The budget margin covers the
        chunks that will decode while the prepare runs: one chunk when the
        prefill shape is already compiled; a compile allowance when it's
        cold (a splice rejection throws the whole prefill away, so
        over-reserving beats racing a multi-second XLA compile — and a cold
        shape happens at most once per power-of-two admission batch)."""
        # One pass: pick the margin up front from the warmth of the LIKELY
        # admission shape (can_admit tokenizes the full prompt — splitting
        # twice would double that work). The guess can overshoot the final
        # take count and land on a different power-of-two shape; the cost of
        # a wrong guess is only a slightly off budget margin.
        guess = min(len(candidates), sess.capacity())
        if sess.prefill_warm(guess):
            margin = 1
        else:
            # reserve up to 8 chunks for the compile, but never so much that
            # admission becomes impossible in principle — cap at half the
            # session's remaining chunks
            # round_slots: a speculative round burns spec_k+1 slots, so the
            # compile reserve is counted in the session's ACTUAL round size
            margin = min(8, max(1, sess.remaining_steps()
                                // (2 * sess.round_slots())))
        take: List = []
        retry: List = []   # transient rejection: no free row RIGHT NOW
        defer: List = []   # permanent for this session: budget/prompt
        for item in candidates:
            if len(take) >= sess.capacity():
                # rows free as requests finish — retry next chunk boundary
                retry.append(item)
            elif sess.can_admit(item.prompt, item.max_new,
                                lookahead_chunks=margin):
                take.append(item)
            else:
                defer.append(item)
        return take, retry, defer

    def _do_prepare(self, sess, take: List):
        """Executor-side admission phase 1: prefill the newcomers WITHOUT
        the engine lock (BatchSession.prepare_admit) so a prefill — which
        may compile a fresh shape, seconds of host time — cannot stall the
        in-flight chunk running concurrently (VERDICT r4 weak #4)."""
        return sess.prepare_admit([p.prompt for p in take],
                                  [p.max_new for p in take],
                                  temperature=[p.temperature for p in take],
                                  top_k=[p.top_k for p in take],
                                  tenants=[p.tenant for p in take],
                                  task_ids=[p.task_id for p in take])
