"""Length buckets + padding — the replacement for pad-everything-to-max.

The reference pads every sentence to the model's max_position_embeddings (514
for mpnet) regardless of true length (reference:
services/preprocessing_service/src/embedding_generator.rs:83-91), so a 6-token
sentence pays a 514-token forward. SURVEY.md §5.7 sizes that waste at ~10-80×.
Here each sequence is padded only to the smallest configured bucket ≥ its
length, and batches are grouped per bucket; batch sizes are likewise bucketed
so the executable cache stays bounded at |length_buckets|×|batch_buckets|
entries (the "recompile storm" guard from SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def choose_bucket(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ length; the largest bucket if none (caller truncates)."""
    for b in buckets:
        if length <= b:
            return b
    return buckets[-1]


def pad_to_bucket(
    seqs: Sequence[Sequence[int]], bucket: int, pad_id: int,
    dtype=np.int32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a list of token-id sequences to [n, bucket] ids + mask.

    `dtype` lets callers ship ids in the narrowest dtype the vocab allows
    (uint16 when vocab ≤ 65535) — halves h2d bytes; the device executable
    casts back to int32."""
    n = len(seqs)
    ids = np.full((n, bucket), pad_id, dtype)
    mask = np.zeros((n, bucket), np.int32)
    for i, s in enumerate(seqs):
        s = list(s[:bucket])
        ids[i, : len(s)] = s
        mask[i, : len(s)] = 1
    return ids, mask


def pad_batch_rows(
    ids: np.ndarray, mask: np.ndarray, batch_bucket: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Pad batch dim up to batch_bucket with all-pad rows; returns real count."""
    n = ids.shape[0]
    if n == batch_bucket:
        return ids, mask, n
    pad_rows = batch_bucket - n
    ids = np.concatenate([ids, np.tile(ids[-1:], (pad_rows, 1))], axis=0)
    mask = np.concatenate([mask, np.zeros((pad_rows, mask.shape[1]), np.int32)], axis=0)
    return ids, mask, n


def pad_ids_rows(
    seqs: Sequence[Sequence[int]], bucket: int, pad_id: int,
    dtype=np.int32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad token-id sequences to [n, bucket] ids + true lengths [n].

    The attention mask is NOT materialized on host: the device executable
    rebuilds it as `arange(bucket) < lengths[:, None]`, halving the
    host→device bytes vs shipping an explicit [n, bucket] mask — on a
    network-attached chip h2d bandwidth is part of the ingest wall.
    `dtype` further narrows the wire: uint16 ids when the vocab fits."""
    n = len(seqs)
    ids = np.full((n, bucket), pad_id, dtype)
    lengths = np.zeros((n,), np.int32)
    for i, s in enumerate(seqs):
        s = list(s[:bucket])
        ids[i, : len(s)] = s
        lengths[i] = len(s)
    return ids, lengths


def pad_batch_rows_ids(
    ids: np.ndarray, lengths: np.ndarray, batch_bucket: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Row-pad (ids, lengths) up to batch_bucket; padding rows get length 0
    (their pooled output is discarded). Returns real row count."""
    n = ids.shape[0]
    assert n <= batch_bucket, (
        f"batch of {n} rows exceeds its batch bucket {batch_bucket}")
    if n == batch_bucket:
        return ids, lengths, n
    pad_rows = batch_bucket - n
    ids = np.concatenate([ids, np.tile(ids[-1:], (pad_rows, 1))], axis=0)
    lengths = np.concatenate([lengths, np.zeros(pad_rows, np.int32)])
    return ids, lengths, n


def padding_stats(
    lengths: Sequence[int], bucket: int, batch_rows: int
) -> Tuple[int, int]:
    """(real_tokens, padded_slots) for one dispatched batch: how many of the
    `batch_rows * bucket` token slots the device will chew on carry real
    tokens vs bucket/row padding. Feeds the engine-plane padding-waste
    gauges (docs/OBSERVABILITY.md) — the quantified version of this module's
    whole reason to exist (SURVEY.md §5.7's 10-80x pad-to-max waste)."""
    real = int(sum(min(int(n), bucket) for n in lengths))
    return real, int(batch_rows) * int(bucket)


def plan_batches(
    lengths: Sequence[int],
    length_buckets: Sequence[int],
    max_batch: int,
) -> List[Tuple[int, List[int]]]:
    """Greedy plan: sort indices by length, group same-bucket runs into batches
    of ≤ max_batch. Returns [(length_bucket, [original indices]), ...]."""
    order = sorted(range(len(lengths)), key=lambda i: lengths[i])
    plans: List[Tuple[int, List[int]]] = []
    cur_bucket = None
    cur: List[int] = []
    for idx in order:
        b = choose_bucket(lengths[idx], length_buckets)
        if b != cur_bucket or len(cur) >= max_batch:
            if cur:
                plans.append((cur_bucket, cur))
            cur_bucket, cur = b, []
        cur.append(idx)
    if cur:
        plans.append((cur_bucket, cur))
    return plans
