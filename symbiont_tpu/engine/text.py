"""Text cleaning, sentence splitting, word tokenization.

Byte-for-byte behavioral parity with the reference's preprocessing core
(reference: services/preprocessing_service/src/main.rs:28-70), which SURVEY.md
§4 flags as untested-with-edge-cases there (multi-byte chars + byte-indexed
slicing). Python str indexing is codepoint-based so the multi-byte hazard
disappears, but the observable behavior matches:

- clean: split on whitespace, join with single spaces (main.rs:28-33);
- split: a sentence ends at each '.', '?' or '!' (delimiter kept, slice
  trimmed); trailing remainder becomes a final sentence; a non-empty text with
  no delimiters is one sentence (main.rs:41-62);
- empty cleaned text is an error at the caller (main.rs:33-39).
"""

from __future__ import annotations

from typing import List

SENTENCE_DELIMS = {".", "?", "!"}


def clean_text(raw: str) -> str:
    return " ".join(raw.split())


def split_sentences(cleaned: str) -> List[str]:
    sentences: List[str] = []
    start = 0
    for i, ch in enumerate(cleaned):
        if ch in SENTENCE_DELIMS:
            if i >= start:
                sentences.append(cleaned[start:i + 1].strip())
                start = i + 1
    if start < len(cleaned):
        remainder = cleaned[start:].strip()
        if remainder:
            sentences.append(remainder)
    if not sentences and cleaned:
        sentences.append(cleaned)
    return sentences


def tokenize_words(cleaned: str) -> List[str]:
    """Whitespace word tokens for the knowledge-graph path
    (TokenizedTextMessage.tokens; the KG stores lowercase-keyed Token nodes —
    reference: services/knowledge_graph_service/src/main.rs:100-125 — but the
    message carries the original-case words)."""
    return cleaned.split()
