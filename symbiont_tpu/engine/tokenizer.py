"""Subword tokenization for the engine (host-side, off-TPU).

The reference tokenizes with HF `tokenizers` configured for fixed padding to
model max + LongestFirst truncation (reference:
services/preprocessing_service/src/embedding_generator.rs:75-99). Here
truncation stays (to model max) but padding moves to the bucketing layer
(engine/bucketing.py) — the whole point of §5.7's redesign.

Two implementations:
- HFTokenizer: loads a tokenizer.json from a local model dir (the format every
  model in BASELINE.md ships). Offline only — no hub download.
- HashTokenizer: deterministic, file-free tokenizer (regex word split + stable
  hash into the vocab). Used by tests and benchmarks so the full pipeline runs
  with zero model assets; NOT semantically meaningful.
"""

from __future__ import annotations

import hashlib
import re
from pathlib import Path
from typing import List, Protocol, Sequence, Tuple


class Tokenizer(Protocol):
    cls_id: int
    sep_id: int
    pad_id: int

    def encode(self, text: str, max_len: int) -> List[int]:
        """Token ids incl. special tokens, truncated to max_len."""
        ...

    def encode_batch(self, texts: Sequence[str], max_len: int) -> List[List[int]]:
        """Batch encode — HF tokenizers parallelizes this in native code."""
        ...

    def encode_pair(self, a: str, b: str, max_len: int) -> Tuple[List[int], List[int]]:
        """(ids, token_type_ids) for cross-encoder input, truncated to max_len."""
        ...


class HFTokenizer:
    def __init__(self, tokenizer_file: str | Path):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(str(tokenizer_file))
        self._tok.no_padding()
        self._tok.no_truncation()

        def _tid(*names: str) -> int:
            for n in names:
                i = self._tok.token_to_id(n)
                if i is not None:
                    return i
            return 0  # reference falls back to id 0 for [PAD]
                      # (embedding_generator.rs:86-90)

        self.cls_id = _tid("[CLS]", "<s>")
        self.sep_id = _tid("[SEP]", "</s>")
        self.pad_id = _tid("[PAD]", "<pad>")

    def _truncate(self, ids: List[int], max_len: int) -> List[int]:
        # LongestFirst truncation parity: keep specials, trim the middle
        if len(ids) > max_len:
            ids = ids[: max_len - 1] + [self.sep_id]
        return ids

    def encode(self, text: str, max_len: int) -> List[int]:
        return self._truncate(self._tok.encode(text).ids, max_len)

    def encode_batch(self, texts: Sequence[str], max_len: int) -> List[List[int]]:
        """One call into the native tokenizer — it parallelizes across texts
        (rayon), vs the serial per-text path the reference uses for whole
        documents (embedding_generator.rs:160-164)."""
        encs = self._tok.encode_batch(list(texts))
        return [self._truncate(e.ids, max_len) for e in encs]

    def encode_pair(self, a: str, b: str, max_len: int) -> Tuple[List[int], List[int]]:
        enc = self._tok.encode(a, b)
        ids = enc.ids
        types = enc.type_ids
        if len(ids) > max_len:
            ids = ids[: max_len - 1] + [self.sep_id]
            types = types[: max_len - 1] + [types[max_len - 2] if max_len > 1 else 0]
        return ids, types


_WORD_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)


class HashTokenizer:
    """Deterministic file-free tokenizer for tests/bench."""

    def __init__(self, vocab_size: int = 30000):
        if vocab_size < 8:
            raise ValueError("vocab_size too small")
        self.vocab_size = vocab_size
        self.pad_id = 0
        self.cls_id = 1
        self.sep_id = 2

    def _id(self, word: str) -> int:
        h = int.from_bytes(hashlib.blake2s(word.lower().encode()).digest()[:4], "little")
        return 3 + (h % (self.vocab_size - 3))

    def encode(self, text: str, max_len: int) -> List[int]:
        ids = [self.cls_id] + [self._id(w) for w in _WORD_RE.findall(text)] + [self.sep_id]
        if len(ids) > max_len:
            ids = ids[: max_len - 1] + [self.sep_id]
        return ids

    def encode_batch(self, texts: Sequence[str], max_len: int) -> List[List[int]]:
        return [self.encode(t, max_len) for t in texts]

    def encode_pair(self, a: str, b: str, max_len: int) -> Tuple[List[int], List[int]]:
        a_ids = [self._id(w) for w in _WORD_RE.findall(a)]
        b_ids = [self._id(w) for w in _WORD_RE.findall(b)]
        ids = [self.cls_id] + a_ids + [self.sep_id] + b_ids + [self.sep_id]
        types = [0] * (len(a_ids) + 2) + [1] * (len(b_ids) + 1)
        if len(ids) > max_len:
            ids = ids[: max_len - 1] + [self.sep_id]
            types = types[: max_len]
        return ids, types


def load_tokenizer(model_dir: str | Path | None, vocab_size: int = 30000) -> Tokenizer:
    """tokenizer.json from the model dir if present, else the hash tokenizer."""
    if model_dir is not None:
        f = Path(model_dir) / "tokenizer.json"
        if f.exists():
            return HFTokenizer(f)
    return HashTokenizer(vocab_size)
