"""The TPU engine — the system's compute center.

In the reference, compute hides inside preprocessing_service as a serial
batch-8 candle loop shared across unbounded spawned tasks (reference:
services/preprocessing_service/src/main.rs:376, embedding_generator.rs:146-216
— a documented contention hazard, SURVEY.md §5.2). Here the engine is a
single-owner component: one process owns the device mesh, all work flows
through an explicit batching queue, and executables are compiled per
(length-bucket, batch-bucket) static shape.

text      : cleaning / sentence split / word tokenize (reference parity)
tokenizer : subword tokenizers (HF tokenizers file, or hash tokenizer for
            file-free tests and benchmarks)
bucketing : length buckets + padding (replaces pad-everything-to-514)
batcher   : async micro-batching queue with deadline flush (latency vs
            throughput policies over one engine)
engine    : TpuEngine — embed / rerank / generate over the mesh
"""

from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.engine.lm import LmEngine

__all__ = ["TpuEngine", "LmEngine"]
