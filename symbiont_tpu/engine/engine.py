"""TpuEngine — single owner of the device mesh; embed / rerank / generate.

Replaces the reference's EmbeddingGenerator (reference:
services/preprocessing_service/src/embedding_generator.rs:134-223) and its
serial batch-8, pad-to-max loop with:

- length-bucketed static shapes (engine/bucketing.py) and a bounded
  (length-bucket × batch-bucket) executable cache — no recompile storms;
- data-parallel batches over the mesh 'data' axis (params replicated,
  batch dim sharded) — the DP row of SURVEY.md §2's parallelism table;
- a single-owner design: services talk to the engine, never to the device,
  removing the reference's concurrent-forward contention hazard (§5.2).

The engine is synchronous at this layer; the async micro-batching facade for
the interactive query path lives in engine/batcher.py.

Concurrency contract ("single owner" made precise): single-owner means this
process — one TpuEngine instance owns the device; no other code touches it.
The engine's entry points (embed_texts / embed_and_search / rerank / warmup)
ARE safe to call from multiple threads concurrently: JAX dispatch is
thread-safe and the XLA runtime serializes device execution per stream, so
interleaved calls only interleave host-side dispatch, never device state.
Two internal locks keep the bookkeeping consistent under that concurrency:
_lock guards the executable cache, _stats_lock guards counters (asserted by
a concurrent stress test). Deliberately NOT serialized: a bulk embed_texts
must not block an interactive rerank/fused query behind its whole batch —
that's the two-queue-policies design of SURVEY.md §7 hard part 4. (LmEngine
is different: its decode loop carries KV-cache state across a long scan, so
it DOES hold its lock for the whole generate call.)
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional, Sequence

import numpy as np

from symbiont_tpu.config import EngineConfig
from symbiont_tpu.engine.bucketing import (
    choose_bucket,
    pad_batch_rows_ids,
    pad_ids_rows,
    pad_to_bucket,
    padding_stats,
    plan_batches,
)
from symbiont_tpu.engine.tokenizer import Tokenizer, load_tokenizer
from symbiont_tpu.models import bert as bert_mod
from symbiont_tpu.models.bert import BertConfig
from symbiont_tpu.obs.hbm import guard_oom, hbm_ledger
from symbiont_tpu.obs.xprof import compile_analysis_for, dispatch_ledger
from symbiont_tpu.utils.telemetry import maybe_profile, metrics

log = logging.getLogger(__name__)


def _start_host_copies(arrays) -> None:
    """Kick off device→host copies for every pending result before any is
    materialized. On a network-attached TPU each synchronous np.asarray pays a
    full round-trip (~100ms); overlapping the copies collapses N round-trips
    into ~one. No-op on backends without copy_to_host_async."""
    for a in arrays:
        try:
            a.copy_to_host_async()
        except AttributeError:
            return


class TpuEngine:
    # max batches fused into one d2h fetch (see _concat in __init__); bounds
    # the transient concat buffer to ~CONCAT_FETCH_MAX × max_batch rows and
    # the concat-executable variety to small operand tuples
    CONCAT_FETCH_MAX = 16

    def __init__(
        self,
        config: Optional[EngineConfig] = None,
        mesh=None,
        params=None,
        model_cfg: Optional[BertConfig] = None,
        tokenizer: Optional[Tokenizer] = None,
        pooling: str = "mean",
        normalize: bool = False,
        cross_params=None,
        cross_cfg: Optional[BertConfig] = None,
    ):
        import jax

        self.config = config or EngineConfig()
        self.mesh = mesh
        self.pooling = pooling
        self.normalize = normalize

        if params is None or model_cfg is None:
            if self.config.model_dir:
                from symbiont_tpu.models.convert import load_bert_model

                params, model_cfg = load_bert_model(self.config.model_dir)
                log.info("loaded checkpoint from %s", self.config.model_dir)
            else:
                # synthetic mode: random weights at the configured dim — full
                # pipeline runs with zero model assets (dev / bench / tests).
                # Depth follows the BASELINE.md checkpoint that dim implies
                # (384→MiniLM-L6, 768→mpnet-base L12, 1024→e5-large L24) so
                # synthetic throughput/MFU numbers are honest for the real
                # model's FLOPs, not a shallower stand-in.
                d = self.config.embedding_dim
                layers = {384: 6, 768: 12, 1024: 24}.get(d, 6 if d <= 512 else 12)
                model_cfg = BertConfig(
                    vocab_size=30000, hidden_size=d,
                    num_layers=layers, num_heads=max(1, d // 64),
                    intermediate_size=4 * d, max_position_embeddings=512,
                    dtype=self.config.dtype)
                params = bert_mod.init_params(jax.random.key(0), model_cfg)
                log.warning("engine running with RANDOM weights (no model_dir)")
        if cross_params is None and (self.config.cross_model_dir
                                     or self.config.rerank_enabled):
            if self.config.cross_model_dir:
                from symbiont_tpu.models.convert import load_bert_model

                cross_params, cross_cfg = load_bert_model(
                    self.config.cross_model_dir, with_pooler=True)
                log.info("loaded cross-encoder from %s",
                         self.config.cross_model_dir)
            else:
                # synthetic cross-encoder: embedder geometry + pooler head —
                # the rerank path runs end-to-end with zero model assets
                cross_cfg = model_cfg
                cross_params = bert_mod.init_params(
                    jax.random.key(1), cross_cfg, with_pooler=True)
                log.warning(
                    "cross-encoder running with RANDOM weights (rerank_enabled "
                    "without cross_model_dir)")
        import dataclasses

        if model_cfg.dtype != self.config.dtype:
            model_cfg = dataclasses.replace(model_cfg, dtype=self.config.dtype)
        attn_impl = self.config.attn_impl
        if attn_impl not in ("auto", "flash", "xla"):
            raise ValueError(
                f"attn_impl must be auto|flash|xla, got {attn_impl!r}")
        # 'auto' resolves to XLA attention for EVERY encoder bucket: with the
        # bf16 softmax path in models/bert.py, XLA's fused attention now
        # beats the pallas flash kernel at all bucket lengths on v5e
        # (measured compute-only: +36% at S=256, +9% at S=512 — flash won
        # these buckets only back when the softmax round-tripped f32).
        # attn_impl='flash' remains an explicit opt-in for memory-bound
        # cases (no S² intermediates; fused backward for training).
        if attn_impl == "auto":
            attn_impl = "xla"
        if model_cfg.attn_impl != attn_impl:
            model_cfg = dataclasses.replace(model_cfg, attn_impl=attn_impl)
        if cross_cfg is not None and cross_cfg.dtype != self.config.dtype:
            cross_cfg = dataclasses.replace(cross_cfg, dtype=self.config.dtype)
        if cross_cfg is not None and cross_cfg.attn_impl != attn_impl:
            cross_cfg = dataclasses.replace(cross_cfg, attn_impl=attn_impl)
        if self.config.quantize != "none":
            # ONCE on host, before device placement: rank-≥2 params become
            # bf16 / per-channel int8 / fp8 at rest (models/quant.py), and
            # the dequant is fused into the jitted forward — XLA reads the
            # narrow representation out of HBM. Parity bars:
            # docs/QUANTIZATION.md, gated in tests/test_quantization.py.
            from symbiont_tpu.models import quant

            params = quant.quantize_params(params, self.config.quantize)
            if cross_params is not None:
                cross_params = quant.quantize_params(cross_params,
                                                     self.config.quantize)
            log.info("engine params quantized: %s", self.config.quantize)
        self.model_cfg = model_cfg
        self.tokenizer = tokenizer or load_tokenizer(self.config.model_dir,
                                                     model_cfg.vocab_size)
        self.cross_params = cross_params
        self.cross_cfg = cross_cfg

        self._lock = threading.Lock()  # guards the executable cache
        self._stats_lock = threading.Lock()  # guards the counters below
        self._exec_cache: OrderedDict = OrderedDict()
        # narrowest id dtype the vocab allows: uint16 halves h2d bytes for
        # every BERT-family vocab ≤ 65535 (MiniLM/bge/e5: 30522; NOT
        # multilingual-mpnet's XLM-R 250002); executables cast back to int32
        self._ids_dtype = (np.uint16 if model_cfg.vocab_size <= 65535
                           else np.int32)
        self._prep_pool = None  # lazy 1-thread pool for the ingest pipeline
        # fused result fetch: batch outputs concatenate on device and come
        # back in ONE d2h copy per group — on a network-attached chip each
        # copy pays ~an RTT of overhead, so N batches fetched separately
        # cost measurably more than one 1.6MB copy (measured +20%
        # bulk-ingest throughput on the v5e tunnel). Grouped at most
        # CONCAT_FETCH_MAX operands per concat: arity (and therefore the
        # jit retrace variety AND the transient duplicate of the group's
        # outputs on device) stays bounded no matter the corpus size.
        import jax as _jax
        import jax.numpy as _jnp

        self._concat = _jax.jit(lambda *xs: _jnp.concatenate(xs, axis=0))

        self._data_parallel = False
        if mesh is not None and self.config.data_parallel:
            if mesh.shape.get("data", 1) > 1:
                self._data_parallel = True
        if self._data_parallel:
            from symbiont_tpu.parallel.sharding import batch_sharding, replicate

            self.params = replicate(mesh, params)
            self._batch_sharding = batch_sharding(mesh)
            self._n_data = mesh.shape["data"]
            if cross_params is not None:
                self.cross_params = replicate(mesh, cross_params)
        else:
            self.params = jax.device_put(params)
            self._batch_sharding = None
            self._n_data = 1
            if cross_params is not None:
                self.cross_params = jax.device_put(cross_params)

        # stats (SURVEY.md §5.5: the reference has none). Mutate via _bump
        # only — bare `stats[k] += 1` is a read-modify-write that loses
        # increments under concurrent entry points. compile_s is first-call
        # wall time of each executable (XLA compiles synchronously inside
        # the first dispatch): an approximation that includes one dispatch,
        # but compiles are seconds and dispatches are microseconds.
        self.stats = {"embed_calls": 0, "sentences_embedded": 0,
                      "rerank_calls": 0, "qsearch_calls": 0, "compiles": 0,
                      "compile_s": 0.0}
        self._register_gauges()
        # dtype-labeled at-rest parameter bytes (docs/OBSERVABILITY.md):
        # the quantization plane's byte budget, readable off /metrics
        from symbiont_tpu.models.quant import param_bytes

        storage = (self.config.quantize if self.config.quantize != "none"
                   else "f32")
        metrics.gauge_set("engine.param_bytes", param_bytes(self.params),
                          labels={"service": "engine", "dtype": storage})
        # hbm attribution plane (obs/hbm.py): the embed/cross params claim
        # their device bytes in the subsystem ledger — weakref-bound, so a
        # dead engine retires the claim like its gauges
        def _engine_param_bytes(eng):
            b = param_bytes(eng.params)
            if eng.cross_params is not None:
                b += param_bytes(eng.cross_params)
            return b

        hbm_ledger.claim("engine.params", self, _engine_param_bytes)

    def _register_gauges(self) -> None:
        """Engine-plane gauges (docs/OBSERVABILITY.md): compile count and
        seconds under a service label. Weakref-bound so the process-global
        registry never pins a dead engine (tests churn through dozens)."""
        def stat(key):
            def read(eng):
                with eng._stats_lock:
                    return eng.stats[key]
            return read

        labels = {"service": "engine"}
        metrics.register_weakref_gauge("engine.compiles", self,
                                       stat("compiles"), labels=labels)
        metrics.register_weakref_gauge("engine.compile_s", self,
                                       stat("compile_s"), labels=labels)
        metrics.register_weakref_gauge("engine.sentences_embedded", self,
                                       stat("sentences_embedded"),
                                       labels=labels)

    def _bump(self, **counts) -> None:
        with self._stats_lock:
            for k, v in counts.items():
                self.stats[k] += v

    # ------------------------------------------------------------------ jit

    def _attn_cfg(self, cfg, L: int):
        """attn_impl='auto' → XLA at every bucket (see __init__: with bf16
        softmax, XLA wins all measured encoder lengths on v5e). The per-
        bucket hook stays so a future chip/length where the kernel wins can
        re-split the policy without touching call sites."""
        del L
        return cfg

    def _get_executable(self, kind: str, L: int, B: int) -> Callable:
        import jax

        key = (kind, L, B)
        with self._lock:
            if key in self._exec_cache:
                self._exec_cache.move_to_end(key)
                return self._exec_cache[key]

        if kind == "embed":
            import jax.numpy as jnp

            cfg, pooling, normalize = (self._attn_cfg(self.model_cfg, L),
                                       self.pooling, self.normalize)
            d2h_bf16 = self.config.dtype == "bfloat16"

            def fn(params, ids, lengths):
                # mask rebuilt on device from lengths (half the h2d bytes);
                # ids may arrive uint16 (another halving — see _ids_dtype);
                # bf16 engines also ship results back as bf16 (half the d2h
                # bytes — on a network-attached chip d2h bandwidth is the
                # bulk-ingest wall), cast to f32 on host
                ids = ids.astype(jnp.int32)
                mask = (jnp.arange(ids.shape[1]) < lengths[:, None]
                        ).astype(jnp.int32)
                emb = bert_mod.embed_sentences(params, ids, mask, cfg,
                                               pooling=pooling,
                                               normalize=normalize)
                return emb.astype(jnp.bfloat16) if d2h_bf16 else emb
        elif kind == "qsearch":
            # fused interactive query: BERT forward + pool + normalize +
            # cosine scores against the device-resident corpus + top-k, ONE
            # compiled program — the whole search hop is a single device
            # round-trip (the split embed→search path pays ≥2; on a
            # network-attached chip each costs ~100ms). With a mesh whose
            # 'data' axis > 1 the corpus arrives row-sharded: each shard
            # scores its own rows and keeps a local top-k, and only the
            # [n_shards × k] candidates cross the interconnect for the
            # global merge (parallel/sharding.corpus_topk — result order
            # identical to the unsharded path, pinned in tests).
            import jax.numpy as jnp

            cfg, pooling = self._attn_cfg(self.model_cfg, L), self.pooling
            cap, k = B  # for qsearch the batch slot carries (capacity, top_k)
            mesh = self.mesh if self._corpus_sharded(cap) else None

            def fn(params, ids, mask, corpus, n_valid):
                ids = ids.astype(jnp.int32)
                emb = bert_mod.embed_sentences(params, ids, mask, cfg,
                                               pooling=pooling, normalize=True)
                q = emb[0].astype(jnp.bfloat16)  # [D]
                if mesh is not None:
                    from symbiont_tpu.parallel.sharding import corpus_topk

                    return corpus_topk(mesh, corpus, q, n_valid, k)
                scores = (corpus.astype(jnp.bfloat16) @ q).astype(jnp.float32)
                valid = jnp.arange(cap) < n_valid
                scores = jnp.where(valid, scores, -jnp.inf)
                return jax.lax.top_k(scores, k)
        elif kind == "rerank":
            import jax.numpy as jnp

            ccfg = self._attn_cfg(self.cross_cfg, L)

            def fn(params, ids, lengths, len_a):
                # mask and token-type ids rebuilt on device from two [B]
                # length vectors (vs two [B, L] matrices over the wire)
                ids = ids.astype(jnp.int32)
                pos = jnp.arange(ids.shape[1])
                mask = (pos < lengths[:, None]).astype(jnp.int32)
                types = ((pos >= len_a[:, None]) & (pos < lengths[:, None])
                         ).astype(jnp.int32)
                return bert_mod.cross_encoder_score(params, ids, mask, ccfg,
                                                    types)
        else:
            raise ValueError(kind)

        jitted = self._time_first_call(jax.jit(fn), key)
        with self._lock:
            # two threads can race the cold-miss check above; the loser
            # discards its wrapper and reuses the winner's, so one shape
            # never compiles (or counts) twice
            if key in self._exec_cache:
                self._exec_cache.move_to_end(key)
                return self._exec_cache[key]
            self._exec_cache[key] = jitted
            while len(self._exec_cache) > self.config.executable_cache_size:
                self._exec_cache.popitem(last=False)
        self._bump(compiles=1)
        return jitted

    def _time_first_call(self, jitted: Callable, key=None) -> Callable:
        """Account the executable's first-call wall time as compile seconds
        (XLA compiles synchronously inside the first dispatch; subsequent
        calls skip straight to the async dispatch). The flag flips BEFORE
        dispatch: two threads can race a cold executable (see the cache-miss
        note in _get_executable), and claiming first keeps the shared
        compile from being counted twice — a lost claim under-counts one
        dispatch, never double-counts a multi-second compile.

        Each claimed compile also lands on the flight-recorder timeline
        (trace id "engine-compiles", obs/device.py): a recompile storm is a
        row of spans in the Perfetto export, not just a counter that rose.

        EVERY call (not just the first) reports its host wall to the
        per-executable dispatch ledger (obs/xprof.py) — kernel-launch
        counts + host dispatch overhead per executable, the compute-plane
        profiler's primary feed. The first call lowers + compiles via AOT
        (obs/xprof.compile_analysis_for) so the XLA cost model AND the
        static memory footprint (temp/argument/output bytes) come off the
        ONE real compile, and later calls dispatch through the Compiled
        object — every call per cache key shares exact shapes, so the AOT
        path is always type-valid; if the backend rejects it we fall back
        to the jitted fn (jit's own cache; at worst one duplicate compile
        on that rare path). Every dispatch runs under the OOM guard: a
        RESOURCE_EXHAUSTED escaping XLA is recorded to the hbm forensics
        plane (postmortem + engine.oom_total{site}) and re-raised."""
        first = [True]
        sig = (f"{key[0]}[L={key[1]},B={key[2]}]" if key is not None
               else "unknown")
        dispatch_fn = [jitted]  # swapped to the AOT Compiled after compile

        def wrapper(*args):
            if not first[0]:
                t0 = time.perf_counter()
                try:
                    with guard_oom(f"engine.{sig}"):
                        out = dispatch_fn[0](*args)
                except TypeError:
                    # AOT call-convention mismatch (backend-specific):
                    # permanently fall back to the jitted fn
                    dispatch_fn[0] = jitted
                    with guard_oom(f"engine.{sig}"):
                        out = jitted(*args)
                dispatch_ledger.note_dispatch(sig, time.perf_counter() - t0)
                return out
            first[0] = False
            # the one real XLA compile happens INSIDE compile_analysis_for
            # (lowered.compile()), so compile_s timing starts before it
            t0 = time.perf_counter()
            start_s = time.time()
            cost, mem, compiled = compile_analysis_for(jitted, args)
            with guard_oom(f"engine.{sig}"):
                if compiled is not None:
                    try:
                        out = compiled(*args)
                        dispatch_fn[0] = compiled
                    except TypeError:
                        out = jitted(*args)
                else:
                    out = jitted(*args)
            dt = time.perf_counter() - t0
            self._bump(compile_s=dt)
            dispatch_ledger.note_compile(sig, cost, memory=mem)
            dispatch_ledger.note_dispatch(sig, dt)
            from symbiont_tpu.obs.device import record_compile_event

            record_compile_event(
                "engine.compile", dt, start_s=start_s, signature=sig)
            return out

        return wrapper

    def _note_padding(self, true_lengths, bucket: int, batch_rows: int,
                      n_real: int) -> None:
        """Bucket padding-waste + batch fill-ratio gauges for one dispatched
        batch (engine/bucketing.py quantified live)."""
        real, total = padding_stats(true_lengths, bucket, batch_rows)
        # decode-plane flight recorder, embed side (obs/engine_timeline.py):
        # the per-flush bucket-occupancy/padding timeline behind the
        # packing-opportunity estimate — host ints already in hand
        from symbiont_tpu.obs.engine_timeline import engine_timeline

        engine_timeline.note_embed_flush(bucket, batch_rows, n_real,
                                         real_tokens=real,
                                         total_tokens=total)
        labels = {"service": "engine"}
        metrics.inc("engine.tokens_real", real, labels=labels)
        metrics.inc("engine.tokens_padding", total - real, labels=labels)
        metrics.gauge_set("engine.batch_fill_ratio",
                          round(n_real / batch_rows, 4) if batch_rows else 0.0,
                          labels=labels)
        metrics.gauge_set("engine.bucket_pad_waste_ratio",
                          round(1.0 - real / total, 4) if total else 0.0,
                          labels=labels)
        if self._n_data > 1 and batch_rows:
            # DP accounting (docs/SCALING.md): rows shard contiguously over
            # the 'data' axis, real rows first, so the trailing replicas
            # carry the padding. Per-replica padding waste names WHICH
            # replicas burn cycles on pad rows, and the balance gauge
            # (min real rows ÷ max real rows) reads 1.0 when every replica
            # does equal useful work.
            per = batch_rows // self._n_data
            real_rows = [min(max(n_real - r * per, 0), per)
                         for r in range(self._n_data)]
            for r, rr in enumerate(real_rows):
                metrics.gauge_set(
                    "batcher.padding_waste",
                    round(1.0 - rr / per, 4) if per else 0.0,
                    labels={"service": "engine", "replica": str(r)})
            mx = max(real_rows)
            metrics.gauge_set("engine.dp_shard_balance",
                              round(min(real_rows) / mx, 4) if mx else 0.0,
                              labels=labels)
            metrics.gauge_set("engine.dp_replicas", self._n_data,
                              labels=labels)

    def _device_batch(self, *arrays: np.ndarray):
        """Move batch-dim-0 arrays to the device (sharded over 'data' when
        data-parallel)."""
        import jax.numpy as jnp

        if self._batch_sharding is not None:
            import jax

            return tuple(jax.device_put(jnp.asarray(a), self._batch_sharding)
                         for a in arrays)
        return tuple(jnp.asarray(a) for a in arrays)

    @property
    def _plan_cap(self) -> int:
        """Rows per planned batch: max_batch clamped to the LARGEST batch
        bucket. A plan chunk bigger than every bucket has no executable
        shape to run in — found by the engine-restart chaos test, where a
        redelivery surge flushed max_batch-sized work through buckets
        smaller than it. Clamping (rather than rounding shapes up) keeps
        the executable set exactly |length_buckets|×|batch_buckets| —
        warmup coverage and the recompile-storm bound stay intact; a surge
        simply splits into top-bucket batches."""
        return min(self.config.max_batch, self.config.batch_buckets[-1])

    def _batch_bucket(self, n: int) -> int:
        b = choose_bucket(n, self.config.batch_buckets)
        if self._n_data > 1:
            # batch must divide over the data axis
            b = max(b, self._n_data)
            b = ((b + self._n_data - 1) // self._n_data) * self._n_data
        return b

    def _corpus_sharded(self, cap: int) -> bool:
        """Whether a [cap, D] corpus operand rides the mesh row-sharded —
        the store shards whenever it holds the same mesh with 'data' > 1
        (its capacity blocks are rounded to the axis size)."""
        return (self.mesh is not None
                and self.mesh.shape.get("data", 1) > 1
                and cap % self.mesh.shape["data"] == 0)

    # ---------------------------------------------------------------- embed

    def _prep_executor(self):
        """The 1-thread pool that tokenizes the NEXT ingest chunk while the
        main thread pads/dispatches the current one."""
        with self._lock:
            if self._prep_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._prep_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="engine-prep")
        return self._prep_pool

    def _dispatch_embed(self, encoded, offset: int, buckets, pending) -> None:
        """Plan + pad + dispatch one tokenized chunk; device calls are async,
        so this returns as soon as the last batch is enqueued. `offset` maps
        chunk-local indices back to the caller's rows."""
        lengths = [len(e) for e in encoded]
        for bucket, indices in plan_batches(lengths, buckets,
                                            self._plan_cap):
            seqs = [encoded[i] for i in indices]
            ids, lens = pad_ids_rows(seqs, bucket, self.tokenizer.pad_id,
                                     dtype=self._ids_dtype)
            bb = self._batch_bucket(len(indices))
            ids, lens, n_real = pad_batch_rows_ids(ids, lens, bb)
            self._note_padding([lengths[i] for i in indices], bucket, bb,
                               n_real)
            fn = self._get_executable("embed", bucket, bb)
            ids_d, lens_d = self._device_batch(ids, lens)
            rows = ([offset + i for i in indices] if offset else indices)
            pending.append((rows, n_real, fn(self.params, ids_d, lens_d)))

    def embed_texts(self, texts: Sequence[str]) -> np.ndarray:
        """Texts → [n, hidden] float32 embeddings. Parity surface of the
        reference's generate_sentence_embeddings (embedding_generator.rs:134).

        Pipelined in three overlapping stages: a prep thread tokenizes chunk
        N+1 while this thread pads/dispatches chunk N (host_prep_chunk texts
        per chunk); jax dispatch is async, so device compute and h↔d
        transfers of successive batches overlap too; all results then
        materialize at once (serializing np.asarray per batch would pay a
        full device round-trip per batch)."""
        if len(texts) == 0:
            return np.zeros((0, self.model_cfg.hidden_size), np.float32)
        max_len = min(self.config.length_buckets[-1],
                      self.model_cfg.max_position_embeddings)
        buckets = [b for b in self.config.length_buckets
                   if b <= self.model_cfg.max_position_embeddings]
        out = np.zeros((len(texts), self.model_cfg.hidden_size), np.float32)
        chunk = self.config.host_prep_chunk
        pending = []
        with maybe_profile("engine.embed"):
            if 0 < chunk < len(texts):
                texts = list(texts)
                pool = self._prep_executor()
                fut = pool.submit(self.tokenizer.encode_batch,
                                  texts[:chunk], max_len)
                for start in range(0, len(texts), chunk):
                    encoded = fut.result()
                    nxt = start + chunk
                    if nxt < len(texts):
                        # prefetch BEFORE dispatching this chunk: tokenize of
                        # chunk N+1 runs while the device chews on chunk N
                        fut = pool.submit(self.tokenizer.encode_batch,
                                          texts[nxt:nxt + chunk], max_len)
                    self._dispatch_embed(encoded, start, buckets, pending)
            else:
                self._dispatch_embed(
                    self.tokenizer.encode_batch(list(texts), max_len),
                    0, buckets, pending)
            if len(pending) > 1 and self._batch_sharding is None:
                # grouped single-copy fetch (see _concat in __init__); the
                # DP-sharded path keeps per-batch fetches — its outputs live
                # sharded across the mesh and gather independently. All
                # group concats dispatch before any materializes, so the
                # d2h copies still overlap.
                fetches = []
                for i in range(0, len(pending), self.CONCAT_FETCH_MAX):
                    grp = pending[i:i + self.CONCAT_FETCH_MAX]
                    res = (grp[0][2] if len(grp) == 1
                           else self._concat(*[b for _, _, b in grp]))
                    fetches.append((grp, res))
                _start_host_copies(res for _, res in fetches)
                for grp, res in fetches:
                    allv = np.asarray(res)
                    off = 0
                    for rows, n_real, res_dev in grp:
                        out[rows] = allv[off:off + n_real]
                        off += res_dev.shape[0]
                dispatch_ledger.note_host_sync("TpuEngine.embed_texts",
                                               len(fetches))
            else:
                _start_host_copies(batch for _, _, batch in pending)
                for rows, n_real, res_dev in pending:
                    out[rows] = np.asarray(res_dev)[:n_real]
                dispatch_ledger.note_host_sync("TpuEngine.embed_texts",
                                               len(pending))
        self._bump(embed_calls=1, sentences_embedded=len(texts))
        return out

    def embed_query(self, text: str) -> np.ndarray:
        """Single query embedding (the tasks.embedding.for_query path)."""
        return self.embed_texts([text])[0]

    def embed_and_search(self, text: str, corpus_dev, n_valid: int,
                         top_k: int):
        """Fused interactive query (the latency half of SURVEY.md §7 hard
        part 4): tokenize on host, then ONE device program does the BERT
        forward, pooling, normalization, cosine scores against the
        device-resident corpus, and top-k. Returns (scores[k], idx[k]) as
        numpy. corpus_dev rows must be L2-normalized ([cap, D] on device)."""
        import jax.numpy as jnp

        max_len = min(self.config.length_buckets[-1],
                      self.model_cfg.max_position_embeddings)
        encoded = self.tokenizer.encode(text, max_len)
        buckets = [b for b in self.config.length_buckets
                   if b <= self.model_cfg.max_position_embeddings]
        bucket = choose_bucket(len(encoded), buckets)
        ids, mask = pad_to_bucket([encoded], bucket, self.tokenizer.pad_id,
                                  dtype=self._ids_dtype)
        cap = corpus_dev.shape[0]
        with maybe_profile("engine.qsearch"):
            fn = self._get_executable("qsearch", bucket, (cap, top_k))
            scores, idx = fn(self.params, jnp.asarray(ids), jnp.asarray(mask),
                             corpus_dev, n_valid)
            _start_host_copies((scores, idx))  # both d2h copies in flight
            self._bump(qsearch_calls=1)
            return np.asarray(scores), np.asarray(idx)

    # --------------------------------------------------------------- rerank

    def rerank(self, query: str, passages: Sequence[str]) -> np.ndarray:
        """Cross-encoder scores for (query, passage) pairs — BASELINE.md #4."""
        if self.cross_params is None or self.cross_cfg is None:
            raise RuntimeError("no cross-encoder model loaded")
        if len(passages) == 0:
            return np.zeros((0,), np.float32)
        max_len = min(self.config.length_buckets[-1],
                      self.cross_cfg.max_position_embeddings)
        pairs = [self.tokenizer.encode_pair(query, p, max_len) for p in passages]
        lengths = [len(ids) for ids, _ in pairs]
        # segment-A width per pair (types are a contiguous 0-run then 1-run);
        # the executable rebuilds mask AND token-type ids from two [B]
        # vectors instead of shipping two [B, L] matrices
        a_widths = [sum(1 for t in types if t == 0) for _, types in pairs]
        buckets = [b for b in self.config.length_buckets
                   if b <= self.cross_cfg.max_position_embeddings]
        out = np.zeros((len(passages),), np.float32)

        pending = []
        with maybe_profile("engine.rerank"):
            for bucket, indices in plan_batches(lengths, buckets,
                                                self._plan_cap):
                ids, lens = pad_ids_rows([pairs[i][0] for i in indices],
                                         bucket, self.tokenizer.pad_id,
                                         dtype=self._ids_dtype)
                len_a = np.asarray([min(a_widths[i], bucket) for i in indices],
                                   np.int32)
                bb = self._batch_bucket(len(indices))
                ids, lens, n_real = pad_batch_rows_ids(ids, lens, bb)
                self._note_padding([lengths[i] for i in indices], bucket, bb,
                                   n_real)
                if len_a.shape[0] < bb:
                    len_a = np.concatenate(
                        [len_a, np.zeros(bb - n_real, np.int32)])
                fn = self._get_executable("rerank", bucket, bb)
                ids_d, lens_d, len_a_d = self._device_batch(ids, lens, len_a)
                pending.append((indices, n_real,
                                fn(self.cross_params, ids_d, lens_d, len_a_d)))
            _start_host_copies(batch for _, _, batch in pending)
            for indices, n_real, res_dev in pending:
                out[indices] = np.asarray(res_dev)[:n_real]
            dispatch_ledger.note_host_sync("TpuEngine.rerank", len(pending))
        self._bump(rerank_calls=1)
        return out

    # ---------------------------------------------------------------- warm

    def warmup(self, buckets: Optional[Sequence[int]] = None,
               batches: Optional[Sequence[int]] = None) -> None:
        """Pre-compile the hot (bucket, batch) executables so first queries
        don't pay the 20-40s TPU compile. Covers the rerank executables too
        when a cross-encoder is loaded — the rerank hop has the tightest
        caller timeout (request_timeout_rerank_s), so it can least afford a
        first-request compile."""
        for L in buckets or self.config.length_buckets[:2]:
            for B in batches or self.config.batch_buckets[:2]:
                bb = self._batch_bucket(B)
                # ids in the runtime wire dtype: a warmup at int32 would
                # compile a signature the uint16 runtime path never hits
                ids = np.ones((bb, L), self._ids_dtype)
                lens = np.full((bb,), L, np.int32)
                fn = self._get_executable("embed", L, bb)
                ids_d, lens_d = self._device_batch(ids, lens)
                np.asarray(fn(self.params, ids_d, lens_d))
                dispatch_ledger.note_host_sync("TpuEngine.warmup")
                if self.cross_params is not None:
                    fn = self._get_executable("rerank", L, bb)
                    len_a = np.full((bb,), L // 2, np.int32)
                    (len_a_d,) = self._device_batch(len_a)
                    np.asarray(fn(self.cross_params, ids_d, lens_d, len_a_d))
                    dispatch_ledger.note_host_sync("TpuEngine.warmup")
