"""LmEngine — autoregressive text generation on TPU (BASELINE.md config #5).

The reference's "generation" is an order-1 Markov chain trained on one
hardcoded sentence that ignores the prompt (reference:
services/text_generator_service/src/main.rs:13-109,120-123). The Markov model
is kept for parity (models/markov.py); this module is the north-star upgrade
named in SURVEY.md §2 item 7: decoder-LM generation (GPT-2 / TinyLlama
layouts) with a static-shape KV-cache decode loop.

TPU shape discipline mirrors the embed path: prompts pad to a small set of
length buckets and max_new_tokens rounds up to a bucket, so each
(prompt_bucket, new_bucket) pair is one compiled executable (the inner
`lax.scan` decode loop never retraces). Sampling params are static too —
they're part of the scan body.

Tokenization: a local HF tokenizer.json when the model dir has one; otherwise
a byte-level tokenizer (vocab 256+specials) so the full pipeline — including
decode back to text — runs with zero model assets.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Optional, Sequence

import numpy as np

from symbiont_tpu.config import LmConfig
from symbiont_tpu.models import gpt as gpt_mod
from symbiont_tpu.models.gpt import GPTConfig
from symbiont_tpu.obs.engine_timeline import engine_timeline
from symbiont_tpu.obs.usage import usage
from symbiont_tpu.resilience.admission import DEFAULT_TENANT
from symbiont_tpu.utils.telemetry import maybe_profile, metrics

log = logging.getLogger(__name__)


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 = bytes, 256 = BOS/pad.

    File-free and lossless (any text round-trips), so synthetic-weight dev
    and bench runs produce decodable output without model assets."""

    vocab_size = 257
    bos_id = 256
    pad_id = 256

    def encode(self, text: str, max_len: int) -> list:
        ids = [self.bos_id] + list(text.encode("utf-8"))
        return ids[:max_len]

    def decode(self, ids) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")


class LmHFTokenizer:
    """tokenizer.json wrapper with decode (generation needs the reverse map)."""

    def __init__(self, tokenizer_file):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(str(tokenizer_file))
        self._tok.no_padding()
        self._tok.no_truncation()
        self.pad_id = self._tok.token_to_id("<pad>") or 0
        eos = None
        for name in ("<|endoftext|>", "</s>", "<|end_of_text|>"):
            eos = self._tok.token_to_id(name)
            if eos is not None:
                break
        self.eos_id = -1 if eos is None else eos
        self.bos_id = self.eos_id if self.eos_id >= 0 else 0

    def encode(self, text: str, max_len: int) -> list:
        return self._tok.encode(text).ids[:max_len]

    def decode(self, ids) -> str:
        return self._tok.decode([int(i) for i in ids])


def _round_up(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class IncrementalDecoder:
    """Turn growing token sequences into stable text deltas.

    `tokenizer.decode` of a prefix is NOT always a prefix of the decode of a
    longer sequence: a multi-byte UTF-8 character straddling a chunk boundary
    decodes to U+FFFD until its continuation bytes arrive. push() therefore
    holds back a trailing replacement-char run (the only unstable region of
    incremental UTF-8 decoding) and only ever emits a confirmed-stable
    prefix; flush() emits the remainder, replacement chars included if the
    model genuinely produced invalid bytes. Concatenated deltas == the full
    decode whenever decode is prefix-stable (true for byte/BPE tokenizers);
    if a tokenizer's decode rewrites earlier output (e.g. decode-time
    cleanup), flush still emits everything past the longest common prefix —
    the tail is never lost, but earlier deltas are not retracted."""

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._emitted = ""

    def _delta_to(self, text: str) -> str:
        if text.startswith(self._emitted) and len(text) > len(self._emitted):
            delta = text[len(self._emitted):]
            self._emitted = text
            return delta
        return ""

    def push(self, all_tokens) -> str:
        text = self._tok.decode(all_tokens)
        stable = text.rstrip("�")
        return self._delta_to(stable)

    def flush(self, all_tokens) -> str:
        text = self._tok.decode(all_tokens)
        if text.startswith(self._emitted):
            return self._delta_to(text)
        # non-prefix-stable decode (e.g. decode-time whitespace cleanup):
        # emit the suffix past the longest common prefix so the terminal
        # output is never silently lost
        i = 0
        for a, b in zip(self._emitted, text):
            if a != b:
                break
            i += 1
        self._emitted = text
        return text[i:]


class LmEngine:
    """Owns LM params + decode executables. Thread-safe, single device owner
    (same stance as TpuEngine — SURVEY.md §5.2's fix for the reference's
    concurrent-forward hazard).

    Tensor-parallel serving: pass a mesh with a 'tensor' axis > 1 and the
    params shard megatron-style across it (parallel/sharding.py) — decode
    then serves models larger than one chip's HBM, with GSPMD inserting the
    TP collectives into the same jitted decode the single-chip path runs
    (SURVEY.md §2: "TP optional, implemented" — now for serving, not just
    training). Requires num_heads, kv_heads, and intermediate_size divisible
    by the tensor axis."""

    def __init__(self, config: Optional[LmConfig] = None, params=None,
                 model_cfg: Optional[GPTConfig] = None, tokenizer=None,
                 mesh=None):
        import dataclasses

        import jax

        self.config = config or LmConfig()
        cfg = self.config

        if params is None or model_cfg is None:
            if cfg.model_dir:
                from symbiont_tpu.models.convert import load_gpt_model

                params, model_cfg = load_gpt_model(cfg.model_dir)
                log.info("loaded LM checkpoint from %s", cfg.model_dir)
            else:
                # synthetic mode: byte-level vocab, random weights — decodable
                # gibberish; throughput-true for bench, asset-free for dev
                model_cfg = GPTConfig(
                    vocab_size=ByteTokenizer.vocab_size,
                    hidden_size=cfg.hidden_size, num_layers=cfg.num_layers,
                    num_heads=cfg.num_heads,
                    intermediate_size=cfg.intermediate_size,
                    max_position_embeddings=cfg.max_positions,
                    arch=cfg.arch, dtype=cfg.dtype)
                params = gpt_mod.init_params(jax.random.key(0), model_cfg)
                log.warning("LM running with RANDOM weights (no lm model_dir)")
        if model_cfg.dtype != cfg.dtype:
            model_cfg = dataclasses.replace(model_cfg, dtype=cfg.dtype)
        attn_impl = cfg.attn_impl
        if attn_impl not in ("auto", "flash", "xla"):
            raise ValueError(f"attn_impl must be auto|flash|xla, got {attn_impl!r}")
        if attn_impl == "auto":
            # XLA everywhere, same story as the encoder engine: with the
            # bf16 softmax path, XLA beats the flash kernel at prefill too
            # (v5e, measured: gpt2 S=256 9.9 vs 15.2 ms, tinyllama-geom
            # S=256 32 vs 39 ms, tied at S=1024). Decode steps (S=1) always
            # run the XLA cache-read path regardless. 'flash' stays as the
            # memory-bound opt-in (no S² intermediates at multi-k prefill).
            attn_impl = "xla"
        if model_cfg.attn_impl != attn_impl:
            model_cfg = dataclasses.replace(model_cfg, attn_impl=attn_impl)
        if model_cfg.kv_quant != cfg.kv_quant:
            # the cache layout is part of the frozen model config so it keys
            # every compiled decode executable (models/gpt.py init_cache)
            model_cfg = dataclasses.replace(model_cfg, kv_quant=cfg.kv_quant)
        self.model_cfg = model_cfg
        self.mesh = None
        if (cfg.tensor_parallel == "on"
                and (mesh is None or mesh.shape.get("tensor", 1) <= 1)):
            # "on" promises sharded decode; booting unsharded because the
            # mesh has no usable tensor axis would be a silent multi-x
            # memory/latency regression — exactly what "on" exists to catch
            raise ValueError(
                "tensor_parallel='on' requires a mesh with a 'tensor' axis "
                f"> 1 (got {None if mesh is None else dict(mesh.shape)})")
        if (mesh is not None and mesh.shape.get("tensor", 1) > 1
                and cfg.tensor_parallel != "off"):
            tp = mesh.shape["tensor"]
            bad = [f"{name} ({val})"
                   for name, val in (("num_heads", model_cfg.num_heads),
                                     ("kv_heads", model_cfg.kv_heads),
                                     ("intermediate_size",
                                      model_cfg.intermediate_size))
                   if val % tp]
            if bad and cfg.tensor_parallel == "on":
                raise ValueError(
                    f"TP decode needs {', '.join(bad)} divisible by the "
                    f"tensor axis ({tp})")
            if bad:
                # "auto": the mesh's tensor axis may exist for the encoder or
                # training — an LM whose head counts don't divide it must
                # still boot (ADVICE r4), just without sharded decode
                log.warning(
                    "LM tensor_parallel=auto: %s not divisible by tensor "
                    "axis (%d); falling back to single-device decode",
                    ", ".join(bad), tp)
            else:
                self.mesh = mesh
                log.info("LM params sharded for TP decode over tensor=%d", tp)
        self.params = self._place_params(params)

        if tokenizer is None:
            tokenizer = ByteTokenizer()
            if cfg.model_dir:
                from pathlib import Path

                f = Path(cfg.model_dir) / "tokenizer.json"
                if f.exists():
                    tokenizer = LmHFTokenizer(f)
        self.tokenizer = tokenizer
        self._key = jax.random.key(cfg.seed)
        self._lock = threading.Lock()
        # prefill shapes already compiled (session starts + admissions):
        # lets the batcher predict whether an admission prefill is ms-cheap
        # or a fresh multi-second XLA compile (GenBatcher._filter_candidates)
        self._prefill_shapes: set = set()
        self.stats = {"generate_calls": 0, "tokens_generated": 0,
                      "decode_s": 0.0}
        # live continuous-batching sessions (BatchSession registers itself);
        # weak so a finished session vanishes from the KV gauges without an
        # explicit close hook. Own lock: sessions register from executor
        # threads while scrapes iterate from the event loop, and WeakSet is
        # not thread-safe (the engine lock is no substitute — it's held for
        # whole decode calls and a scrape must never block behind one).
        self._sessions: "weakref.WeakSet" = weakref.WeakSet()
        self._sessions_lock = threading.Lock()
        self._register_gauges()

    def _register_gauges(self) -> None:
        """Engine-plane decode gauges (docs/OBSERVABILITY.md): KV-cache row
        occupancy across live sessions, and cumulative decode tokens/s.
        Weakref-bound so the process-global registry never pins a dead
        engine."""
        def kv_rows(active_only: bool):
            def read(lm):
                with lm._sessions_lock:
                    sessions = list(lm._sessions)
                total = 0
                for sess in sessions:
                    if sess.done():
                        continue
                    total += (sum(1 for r in sess.rows if r is not None)
                              if active_only else sess.bb)
                return total
            return read

        def tok_per_s(lm):
            # lockless read: the engine lock is held for whole decode calls,
            # and a scrape must never block seconds behind one. Two GIL-
            # atomic dict reads can straddle an update — a gauge tolerates
            # that; a frozen /metrics endpoint doesn't.
            toks, secs = lm.stats["tokens_generated"], lm.stats["decode_s"]
            return toks / secs if secs > 0 else 0.0

        def kv_bytes(lm):
            # dtype-adjusted occupancy: actual at-rest bytes of every live
            # session's cache (int8 slabs + scale planes when kv_quant is
            # on) — the companion to the row counts above, so capacity
            # planning sees bytes, not just rows
            with lm._sessions_lock:
                sessions = list(lm._sessions)
            return sum(gpt_mod.cache_bytes(s._cache) for s in sessions
                       if not s.done())

        def kv_rows_per_gib(lm):
            # how many session rows one GiB of HBM holds at the live
            # geometry and cache dtype — the "dtype-adjusted capacity"
            # number (int8 ≈ 2× bf16's, ≈ 4× f32's)
            with lm._sessions_lock:
                sessions = [s for s in lm._sessions if not s.done()]
            total = sum(gpt_mod.cache_bytes(s._cache) for s in sessions)
            rows = sum(s.bb for s in sessions)
            return round(rows * (1 << 30) / total, 1) if total else 0.0

        def kv_stranded(lm):
            # rows allocated in dense max-length slabs but NOT live (the
            # batch-bucket padding + finished/cancelled rows a paged KV
            # layout would reclaim — ROADMAP item 2's target number)
            with lm._sessions_lock:
                sessions = [s for s in lm._sessions if not s.done()]
            alloc = sum(s.bb for s in sessions)
            live = sum(sum(1 for r in s.rows if r is not None)
                       for s in sessions)
            return alloc - live

        labels = {"service": "lm",
                  "kv_dtype": ("int8" if self.model_cfg.kv_quant == "int8"
                               else self.model_cfg.dtype)}
        metrics.register_weakref_gauge("lm.kv_stranded_rows", self,
                                       kv_stranded, labels=labels)
        metrics.register_weakref_gauge("lm.kv_rows_active", self,
                                       kv_rows(True), labels=labels)
        metrics.register_weakref_gauge("lm.kv_rows_allocated", self,
                                       kv_rows(False), labels=labels)
        metrics.register_weakref_gauge("lm.kv_cache_bytes", self,
                                       kv_bytes, labels=labels)
        metrics.register_weakref_gauge("lm.kv_rows_per_gib", self,
                                       kv_rows_per_gib, labels=labels)
        metrics.register_weakref_gauge("lm.decode_tok_per_s", self,
                                       tok_per_s, labels=labels)

    def _note_param_bytes(self, params, storage) -> None:
        """Dtype-labeled at-rest parameter bytes (docs/OBSERVABILITY.md) —
        the LM half of the quantization plane's byte budget."""
        from symbiont_tpu.models.quant import param_bytes

        metrics.gauge_set("lm.param_bytes", param_bytes(params),
                          labels={"service": "lm", "dtype": str(storage)})

    def _place_params(self, params):
        """ONE home for parameter placement: megatron-sharded over the mesh's
        'tensor' axis when TP serving is on, plain device_put otherwise.
        Used by __init__ and every online-fine-tune sync (update_params).

        Params are cast to the model dtype AT REST: decode already computes
        in model dtype (forward casts at trace time), so storing f32 only
        doubled HBM residency (TinyLlama: 4.1 GB vs 2.1 GB) and made every
        chunked-decode call re-convert the full parameter set (the fused
        generate hoists the convert once per call; a chunk loop pays it per
        chunk).

        LmConfig.quantize != "none" quantizes here too (once per placement,
        host-side), so online fine-tune syncs re-quantize their f32 masters
        transparently. Quantized placement composes with TP: shard_params
        places QuantTensor codes by the kernel's own PartitionSpec and the
        per-output-channel scales on the kernel's last-axis entry
        (parallel/sharding.py), so `quantize=int8` + `tensor>1` decodes
        sharded AND narrow — the PR 7 fallback (unquantized params on any
        mesh, with a warning) is gone."""
        import jax
        import jax.numpy as jnp

        mode = self.config.quantize
        dtype = jnp.dtype(self.model_cfg.dtype)
        if mode != "none":
            from symbiont_tpu.models import quant

            # cast FIRST, quantize SECOND: the other order let the model-
            # dtype sweep undo f16's bf16-at-rest whenever the compute dtype
            # was wider (f32 compute silently re-widened the weights while
            # the param_bytes gauge still said f16). Quantized rank-≥2
            # leaves now always end narrow; the trace-time entry cast
            # upcasts them on-chip, so HBM reads stay halved regardless of
            # compute dtype.
            params = quant.cast_params(params, dtype)
            params = quant.quantize_params(params, mode)
        else:
            params = jax.tree.map(
                lambda a: a.astype(dtype)
                if (hasattr(a, "dtype")
                    and jnp.issubdtype(a.dtype, jnp.floating))
                else a, params)
        storage = mode if mode != "none" else self.model_cfg.dtype
        self._note_param_bytes(params, storage)
        if self.mesh is None:
            return jax.device_put(params)
        from symbiont_tpu.parallel.sharding import (
            gpt_param_sharding,
            shard_params,
        )

        return shard_params(
            self.mesh, params,
            gpt_param_sharding(self.mesh, params, arch=self.model_cfg.arch))

    # ------------------------------------------------------------------ gen

    def _prepare_prompts(self, prompts: Sequence[str], max_new: int,
                         min_rows: int = 1):
        """Shared decode preamble: pick the new-token bucket, validate it
        fits, encode prompts (tail-trim to the largest usable prompt bucket,
        BOS fallback for empty), pad to a power-of-two batch bucket so the
        executable count stays log-bounded (≥ min_rows — sessions reserve
        headroom rows for mid-decode admission). Returns
        (prompt_ids [bb, P], prompt_mask [bb, P], new_bucket)."""
        cfg = self.config
        new_bucket = _round_up(max_new, cfg.new_token_buckets)
        # P + new_bucket must fit in max_position_embeddings, so prompt
        # buckets above that cap are unusable for this request.
        cap = self.model_cfg.max_position_embeddings - new_bucket
        if cap < 1:
            raise ValueError(
                f"max_new_tokens {max_new} (bucket {new_bucket}) leaves no "
                f"room in {self.model_cfg.max_position_embeddings} positions")
        avail = [b for b in cfg.prompt_buckets if b <= cap] or [cap]
        encoded = []
        for prompt in prompts:
            ids = self.tokenizer.encode(prompt or "", 1 << 30)
            ids = ids[-avail[-1]:]  # keep the tail: recent context wins
            if not ids:
                ids = [getattr(self.tokenizer, "bos_id", 0)]
            encoded.append(ids)
        B = len(encoded)
        bb = 1 << (B - 1).bit_length() if B > 1 else 1
        if min_rows > 1:
            bb = max(bb, 1 << (min_rows - 1).bit_length())
        P = _round_up(max(len(e) for e in encoded), avail)
        pad = getattr(self.tokenizer, "pad_id", 0)
        bos = getattr(self.tokenizer, "bos_id", 0)
        prompt_ids = np.full((bb, P), pad, np.int32)
        prompt_mask = np.zeros((bb, P), np.int32)
        for i, ids in enumerate(encoded):
            prompt_ids[i, : len(ids)] = ids
            prompt_mask[i, : len(ids)] = 1
        for i in range(B, bb):  # padding rows: minimal one-token prompt
            prompt_ids[i, 0] = bos
            prompt_mask[i, 0] = 1
        return prompt_ids, prompt_mask, new_bucket

    def generate(self, prompt: str, max_new_tokens: int,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None) -> str:
        """Prompt → generated text (the tasks.generation.text LM backend)."""
        return self.generate_batch([prompt], [max_new_tokens],
                                   temperature=temperature, top_k=top_k)[0]

    def _norm_sampling_rows(self, value, default, bb: int, n: int, cast):
        """Scalar-or-per-request sampling param → per-row list of length bb
        (batch bucket). None → engine default (element-wise too); padding
        rows decode greedily (their output is discarded)."""
        if value is None:
            value = default
        if isinstance(value, (list, tuple, np.ndarray)):
            if len(value) != n:
                raise ValueError(
                    f"per-request sampling list length {len(value)} != {n}")
            rows = [cast(default if v is None else v) for v in value]
        else:
            rows = [cast(value)] * n
        return rows + [cast(0)] * (bb - n)

    def generate_batch(self, prompts: Sequence[str],
                       max_new_tokens: Sequence[int],
                       temperature=None, top_k=None) -> list:
        """Batched decode: B prompts through ONE (prompt_bucket, new_bucket)
        executable — concurrent generation requests share the decode loop's
        weight reads instead of serializing B single-row decodes. Rows are
        right-aligned internally by gpt.generate, so each row's output is
        independent of its batchmates (greedy decode of a batch == greedy
        decode of each row alone; asserted in tests). Per-request
        max_new_tokens trim a shared new-token bucket; temperature/top_k may
        be scalars or per-request sequences (sampling params are traced
        per-row vectors in the decode executable, so requests with different
        sampling still share one batch)."""
        import jax
        import jax.numpy as jnp

        cfg = self.config
        if len(prompts) != len(max_new_tokens):
            raise ValueError("prompts and max_new_tokens length mismatch")
        prompt_ids, prompt_mask, new_bucket = self._prepare_prompts(
            prompts, max(max_new_tokens))
        bb, n = prompt_ids.shape[0], len(prompts)
        temps = self._norm_sampling_rows(temperature, cfg.temperature,
                                         bb, n, float)
        ks = self._norm_sampling_rows(top_k, cfg.top_k, bb, n, int)
        eos_id = getattr(self.tokenizer, "eos_id", -1)
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            t0 = time.perf_counter()
            with maybe_profile("engine.generate"):
                tokens, lengths = gpt_mod.generate(
                    self.params, jnp.asarray(prompt_ids),
                    jnp.asarray(prompt_mask),
                    sub, self.model_cfg, max_new_tokens=new_bucket,
                    temperature=temps, top_k=ks,
                    eos_id=int(eos_id))
                tokens = np.asarray(tokens)  # materialize → full decode done
            lengths = np.asarray(lengths)
            dt = time.perf_counter() - t0
            self.stats["generate_calls"] += 1
            self.stats["decode_s"] += dt
            out = []
            for i, want in enumerate(max_new_tokens):  # drops padding rows
                n = min(int(lengths[i]), int(want))
                self.stats["tokens_generated"] += n
                out.append(self.tokenizer.decode(tokens[i, :n]))
        return out

    def generate_stream(self, prompt: str, max_new_tokens: int,
                        temperature: Optional[float] = None,
                        top_k: Optional[int] = None,
                        tenant: Optional[str] = None):
        """Streaming decode: yields text deltas as chunks of tokens finish
        (SURVEY.md §7 hard part #5: "streaming tokens back out through
        NATS→SSE"). Prefill + one compiled chunk-scan executable per
        (prompt_bucket, chunk) pair, re-invoked with carried device state —
        time-to-first-chunk is prefill + stream_chunk steps instead of the
        full decode. Greedy streaming concatenates to exactly generate()'s
        output in float32 (asserted in tests); under bfloat16 the chunked
        and full-scan executables may round differently, so greedy outputs
        can diverge at argmax near-ties (pronounced with random weights,
        whose logits are nearly uniform — real checkpoints have margins)."""
        import jax
        import jax.numpy as jnp

        cfg = self.config
        temperature = cfg.temperature if temperature is None else temperature
        top_k = cfg.top_k if top_k is None else top_k

        prompt_ids, prompt_mask, new_bucket = self._prepare_prompts(
            [prompt], max_new_tokens)
        # largest bucket caps the request (same clamp generate() applies via
        # its scan length) — the cache has exactly new_bucket decode slots
        max_new_tokens = min(max_new_tokens, new_bucket)
        # usage ledger (obs/usage.py): prompt tokens are known exactly here,
        # host-side, before any device work
        tenant = tenant or DEFAULT_TENANT
        usage.note(tenant, tokens_in=int(prompt_mask[0].sum()))
        eos_id = getattr(self.tokenizer, "eos_id", -1)
        chunk = min(cfg.stream_chunk, new_bucket)

        # Lock discipline: the engine lock is held only around device work
        # (prefill, each decode_chunk) and NEVER across a yield — a stalled
        # SSE consumer must not starve concurrent generate()/generate_batch()
        # callers waiting on the same lock. This is safe because the KV cache
        # is owned by this generator frame: decode_chunk consumes the carry
        # (cache/logits/pos/done are DONATED and reassigned each chunk;
        # params read-only), so other engine calls interleaving between
        # chunks can't observe or mutate this stream's state. The stream
        # stays consumer-paced: nothing decodes while the consumer is
        # parked between deltas.
        decode_s = 0.0
        with self._lock:
            # timers start inside the lock: decode_s counts this stream's own
            # device work, not time spent waiting on other callers
            t0 = time.perf_counter()
            self._key, sub = jax.random.split(self._key)
            cache, logits, kv_valid, prompt_len = gpt_mod.prefill(
                self.params, jnp.asarray(prompt_ids), jnp.asarray(prompt_mask),
                self.model_cfg, new_bucket)
            decode_s += time.perf_counter() - t0
        done = jnp.zeros((prompt_ids.shape[0],), bool)
        pos = prompt_len
        all_tokens: list = []
        decoder = IncrementalDecoder(self.tokenizer)
        stop = False
        try:
            while len(all_tokens) < max_new_tokens and not stop:
                sub, use = jax.random.split(sub)
                keys = jax.random.split(use, chunk)
                with self._lock:
                    t1 = time.perf_counter()
                    (cache, logits, pos, done, toks,
                     counted) = gpt_mod.decode_chunk(
                        self.params, cache, logits, pos, done, kv_valid, keys,
                        self.model_cfg, temperature=float(temperature),
                        top_k=int(top_k), eos_id=int(eos_id))
                    toks = np.asarray(toks)[0]
                    counted = np.asarray(counted)[0]
                    decode_s += time.perf_counter() - t1
                for t, c in zip(toks, counted):
                    if not c:  # EOS (or a post-EOS slot): stream ends here
                        stop = True
                        break
                    all_tokens.append(int(t))
                    if len(all_tokens) >= max_new_tokens:
                        break
                delta = decoder.push(all_tokens)
                if delta:
                    yield delta
            final_delta = decoder.flush(all_tokens)
            if final_delta:
                yield final_delta
        finally:
            # runs on normal exit AND on generator close (client disconnect)
            usage.note(tenant, tokens_out=len(all_tokens),
                       kv_row_seconds=decode_s * prompt_ids.shape[0])
            with self._lock:
                self.stats["generate_calls"] += 1
                self.stats["tokens_generated"] += len(all_tokens)
                self.stats["decode_s"] += decode_s

    # ----------------------------------------------------- continuous batch

    def start_session(self, prompts: Sequence[str],
                      max_new_tokens: Sequence[int],
                      temperature=None, top_k=None,
                      tenants=None) -> "BatchSession":
        """Open a chunked batch decode that new requests can JOIN at chunk
        boundaries (continuous batching — the GenBatcher upgrade over
        flush-window-only batching; VERDICT r3 item 3). Drive it with
        session.step(); admit newcomers with session.admit(). `tenants`
        (one per prompt; default lane otherwise) routes the usage ledger
        — obs/usage.py."""
        return BatchSession(self, prompts, max_new_tokens, temperature,
                            top_k, tenants=tenants)

    def kv_rows_allocated(self) -> int:
        """Batch rows allocated across live decode sessions — the number
        the `lm.kv_rows_allocated` gauge exports, readable synchronously
        for admission decisions."""
        with self._sessions_lock:
            return sum(s.bb for s in self._sessions if not s.done())

    def kv_row_counts(self) -> tuple:
        """(live, allocated) decode rows across live sessions in ONE
        sessions-lock pass — the engine-timeline step events read both at
        every chunk boundary."""
        with self._sessions_lock:
            sessions = [s for s in self._sessions if not s.done()]
        alloc = sum(s.bb for s in sessions)
        live = sum(sum(1 for r in s.rows if r is not None)
                   for s in sessions)
        return live, alloc

    def can_admit(self, n_rows: int = 1, max_kv_rows: int = 0) -> bool:
        """Capacity-aware generation admission (resilience/admission.py):
        may `n_rows` more decode rows start without pushing allocated KV
        rows past `max_kv_rows`? The API edge consults this BEFORE
        accepting a generation stream, so overload answers 429 instead of
        growing KV caches until the device OOMs. cap <= 0 = unbounded
        (the pre-plane behavior)."""
        if max_kv_rows <= 0:
            return True
        return self.kv_rows_allocated() + max(1, int(n_rows)) <= max_kv_rows

    def update_params(self, params) -> None:
        """Swap in new model parameters (online fine-tune sync,
        train/online.py). Serialized on the engine lock so no decode is
        mid-flight on the old buffers; an in-progress stream picks up the new
        params at its next chunk (its KV cache entries from the old params
        remain valid context — same contract as any incremental fine-tune).
        The caller must hand over buffers it will not later donate or mutate
        (OnlineLmTrainer passes a copy)."""
        with self._lock:
            self.params = self._place_params(params)

    def warmup(self, new_bucket: Optional[int] = None) -> None:
        """Pre-compile the hot (prompt, new) executable pair."""
        self.generate("warmup", new_bucket or self.config.new_token_buckets[0])


def _norm_tenants(tenants, n: int) -> list:
    """Per-row tenant list of length n (default lane where unspecified) —
    the usage ledger's routing (obs/usage.py)."""
    if tenants is None:
        return [DEFAULT_TENANT] * n
    if len(tenants) != n:
        raise ValueError(f"tenants list length {len(tenants)} != {n}")
    return [t or DEFAULT_TENANT for t in tenants]


def _real_token_rows(prompt_ids, prompt_mask, n: int) -> list:
    """The first `n` rows' REAL token ids (padding stripped) as plain int
    lists — host numpy in, host lists out; the prefix-share probe's input."""
    out = []
    for i in range(n):
        length = int(prompt_mask[i].sum())
        out.append(prompt_ids[i, :length].tolist())
    return out


class _SessionRow:
    __slots__ = ("tag", "want", "tokens", "tenant", "created", "first_tok")

    def __init__(self, tag: int, want: int, tenant: str = DEFAULT_TENANT,
                 created: Optional[float] = None):
        self.tag = tag
        self.want = want
        self.tokens: list = []
        # usage ledger + engine-side TTFT (obs/engine_timeline.py): the
        # fairness-lane tenant this row bills to, when the row's PREFILL
        # started (splice passes prepare_admit's entry time — a spliced
        # row's TTFT must include its tokenize/prefill/chunk-boundary
        # wait, not start at the splice), and when its first token
        # materialized on host
        self.tenant = tenant
        self.created = time.perf_counter() if created is None else created
        self.first_tok: Optional[float] = None


class BatchSession:
    """An in-flight chunked batch decode that requests can JOIN at chunk
    boundaries (continuous batching).

    GenBatcher's flush-window batching only merged requests that arrived
    within one deadline window; everything else serialized behind the whole
    decode. A session decodes in stream_chunk-step chunks and, between
    chunks, splices newly-prefilled rows into free slots (row-padding from
    the power-of-two batch bucket, or rows that already finished) via
    gpt.merge_rows — an admitted request's output is EXACTLY what a
    standalone decode would produce (gap cache slots masked, logical
    positions carried; asserted in tests/test_lm_engine.py).

    Threading: device work runs under the engine lock; host bookkeeping is
    single-caller (GenBatcher interleaves admit()/step() sequentially).
    """

    def __init__(self, lm: LmEngine, prompts: Sequence[str],
                 max_new_tokens: Sequence[int], temperature=None,
                 top_k=None, tenants=None):
        import jax
        import jax.numpy as jnp

        cfg = lm.config
        self.lm = lm
        n = len(prompts)
        if n != len(max_new_tokens):
            raise ValueError("prompts and max_new_tokens length mismatch")
        prompt_ids, prompt_mask, self.new_bucket = lm._prepare_prompts(
            prompts, max(max_new_tokens), min_rows=cfg.session_min_rows)
        self.bb, self.P = prompt_ids.shape
        self.chunk = max(1, min(cfg.stream_chunk, self.new_bucket))
        self._temps = lm._norm_sampling_rows(temperature, cfg.temperature,
                                             self.bb, n, float)
        self._ks = lm._norm_sampling_rows(top_k, cfg.top_k, self.bb, n, int)
        self._eos = int(getattr(lm.tokenizer, "eos_id", -1))
        self._next_tag = 0
        row_tenants = _norm_tenants(tenants, n)
        self.rows: list = []
        for i, w in enumerate(max_new_tokens):
            self.rows.append(_SessionRow(self._next_tag,
                                         min(int(w), self.new_bucket),
                                         tenant=row_tenants[i]))
            self._next_tag += 1
        self.rows += [None] * (self.bb - n)  # free slots from the row bucket
        self.steps_done = 0
        self.decode_s = 0.0
        # decode-plane probes, all on host data already in hand
        # (obs/engine_timeline.py): token-id prefix overlap vs recently
        # admitted prompts, and exact prompt-token billing per tenant
        share = engine_timeline.prompt_prefix_share(
            _real_token_rows(prompt_ids, prompt_mask, n))
        for i in range(n):
            usage.note(row_tenants[i],
                       tokens_in=int(prompt_mask[i].sum()))
        with lm._lock:
            lm._key, self._sub = jax.random.split(lm._key)
            t0 = time.perf_counter()
            (self._cache, self._logits, self._kv_valid,
             prompt_len) = gpt_mod.prefill(
                lm.params, jnp.asarray(prompt_ids), jnp.asarray(prompt_mask),
                lm.model_cfg, self.new_bucket)
            prefill_s = time.perf_counter() - t0
            self.decode_s += prefill_s
            lm.stats["sessions"] = lm.stats.get("sessions", 0) + 1
        engine_timeline.note_admit(rows=n, prefill_ms=prefill_s * 1000.0,
                                  prefix_share=share, kind="start")
        lm._prefill_shapes.add((self.bb, self.P, self.new_bucket))
        with lm._sessions_lock:  # weak: KV-occupancy gauges see live sessions
            lm._sessions.add(self)
        self._pos = prompt_len
        self._done = jnp.zeros((self.bb,), bool)

    # ------------------------------------------------------------ admission

    def capacity(self) -> int:
        return sum(1 for r in self.rows if r is None)

    def remaining_steps(self) -> int:
        return self.new_bucket - self.steps_done

    def done(self) -> bool:
        return all(r is None for r in self.rows) or self.remaining_steps() <= 0

    def can_admit(self, prompt: str, max_new: int,
                  lookahead_chunks: int = 0) -> bool:
        """A newcomer joins only if a row slot is free, its budget fits the
        steps this session still has, and its prompt fits the session's
        prompt bucket untrimmed (a longer prompt would lose more context
        than a standalone decode — leave it for the next session).
        `lookahead_chunks` reserves budget for chunks that will decode
        between this check and the actual splice (the prepare/splice split
        runs the newcomer's prefill concurrently with one in-flight chunk)."""
        if (self.capacity() == 0
                or int(max_new) > self.remaining_steps()
                - lookahead_chunks * self.chunk):
            return False
        return len(self.lm.tokenizer.encode(prompt or "", self.P + 1)) <= self.P

    @staticmethod
    def _admission_rows(k: int) -> int:
        """Rows an admission prefill pads to (power-of-two batch bucket).
        Single source for prepare_admit AND prefill_warm — the warm/cold
        prediction is only right while they agree."""
        return 1 << (k - 1).bit_length() if k > 1 else 1

    def prefill_warm(self, k: int) -> bool:
        """Whether admitting k newcomers hits an already-compiled prefill
        shape — prepare_admit then costs milliseconds, not a fresh XLA
        compile (the batcher sizes its budget reservation by this)."""
        bb2 = self._admission_rows(k)
        return (bb2, self.P, self.new_bucket) in self.lm._prefill_shapes

    def prepare_admit(self, prompts: Sequence[str],
                      max_new_tokens: Sequence[int],
                      temperature=None, top_k=None, tenants=None) -> dict:
        """Phase 1 of admission: tokenize + device prefill, WITHOUT the
        engine lock — so a newcomer's prefill (which may compile a fresh
        (batch, P) shape, seconds of host time) cannot stall the in-flight
        batch's next chunk (VERDICT r4 weak #4). Lock-free is safe: params
        are immutable jax buffers read via one atomic attribute load; a
        concurrent update_params swap means the newcomer prefills on the
        old params — the same contract an in-progress stream already has.
        Returns an opaque blob for splice(); no session state is touched."""
        import jax.numpy as jnp

        cfg = self.lm.config
        t_enter = time.perf_counter()  # TTFT origin for the spliced rows
        k = len(prompts)
        bb2 = self._admission_rows(k)
        pad = getattr(self.lm.tokenizer, "pad_id", 0)
        bos = getattr(self.lm.tokenizer, "bos_id", 0)
        ids = np.full((bb2, self.P), pad, np.int32)
        mask = np.zeros((bb2, self.P), np.int32)
        for j, prompt in enumerate(prompts):
            enc = self.lm.tokenizer.encode(prompt or "", 1 << 30)[-self.P:]
            if not enc:
                enc = [bos]
            ids[j, :len(enc)] = enc
            mask[j, :len(enc)] = 1
        for j in range(k, bb2):
            ids[j, 0] = bos
            mask[j, 0] = 1
        # prefix-share probe + exact prompt-token counts BEFORE device
        # work: both read only the host arrays built above
        share = engine_timeline.prompt_prefix_share(
            _real_token_rows(ids, mask, k))
        n_tokens = [int(mask[j].sum()) for j in range(k)]
        params = self.lm.params  # snapshot; immutable buffers
        t0 = time.perf_counter()
        (cache_b, logits_b, kv_valid_b, pos_b) = gpt_mod.prefill(
            params, jnp.asarray(ids), jnp.asarray(mask),
            self.lm.model_cfg, self.new_bucket)
        self.lm._prefill_shapes.add((bb2, self.P, self.new_bucket))
        return {"k": k, "bb2": bb2, "cache": cache_b, "logits": logits_b,
                "kv_valid": kv_valid_b, "pos": pos_b,
                "max_new": [int(w) for w in max_new_tokens],
                "temps": self.lm._norm_sampling_rows(
                    temperature, cfg.temperature, bb2, k, float),
                "ks": self.lm._norm_sampling_rows(
                    top_k, cfg.top_k, bb2, k, int),
                "tenants": _norm_tenants(tenants, k),
                "n_tokens": n_tokens,
                "prefix_share": share,
                "t_enter": t_enter,
                "prefill_s": time.perf_counter() - t0}

    def splice(self, prep: dict) -> list:
        """Phase 2: merge prepared rows into free slots at the current chunk
        boundary. Cheap under the lock — one merge_rows dispatch, no
        prefill. Returns a tag per prepared newcomer, or None where the
        request no longer fits (chunks decoded between prepare and splice
        shrank the remaining budget — truncating would break standalone
        equivalence, so the caller re-queues those for the next session)."""
        import jax.numpy as jnp

        free = [i for i, r in enumerate(self.rows) if r is None]
        row_map = np.full((self.bb,), -1, np.int32)
        tags: list = []
        taken = 0
        for j in range(prep["k"]):
            if (taken >= len(free)
                    or prep["max_new"][j] > self.remaining_steps()):
                tags.append(None)
                continue
            i = free[taken]
            taken += 1
            row_map[i] = j
            self.rows[i] = _SessionRow(self._next_tag, prep["max_new"][j],
                                       tenant=prep.get("tenants",
                                                       [DEFAULT_TENANT]
                                                       * prep["k"])[j],
                                       created=prep.get("t_enter"))
            usage.note(self.rows[i].tenant,
                       tokens_in=prep.get("n_tokens", [0] * prep["k"])[j])
            tags.append(self._next_tag)
            self._next_tag += 1
            self._temps[i] = prep["temps"][j]
            self._ks[i] = prep["ks"][j]
        if taken == 0:
            # even a fully-rejected admission paid its prefill — keep it in
            # the timing stats or wasted cold-compile work becomes invisible
            with self.lm._lock:
                self.decode_s += prep["prefill_s"]
            return tags
        with self.lm._lock:
            t0 = time.perf_counter()
            done_b = jnp.zeros((prep["bb2"],), bool)
            (self._cache, self._logits, self._pos, self._done,
             self._kv_valid) = gpt_mod.merge_rows(
                self._cache, self._logits, self._pos, self._done,
                self._kv_valid, prep["cache"], prep["logits"], prep["pos"],
                done_b, prep["kv_valid"], jnp.asarray(row_map),
                prompt_width=self.P)
            self.decode_s += time.perf_counter() - t0 + prep["prefill_s"]
            self.lm.stats["admitted"] = (self.lm.stats.get("admitted", 0)
                                         + taken)
        engine_timeline.note_admit(
            rows=taken, prefill_ms=prep["prefill_s"] * 1000.0,
            prefix_share=prep.get("prefix_share"), kind="splice")
        return tags

    def admit(self, prompts: Sequence[str], max_new_tokens: Sequence[int],
              temperature=None, top_k=None, tenants=None) -> list:
        """One-shot admission (prepare + splice back-to-back, no chunks in
        between so nothing can be rejected). Caller pre-filters with
        can_admit. Returns the tags identifying each admitted request in
        step() results."""
        tags = self.splice(self.prepare_admit(
            prompts, max_new_tokens, temperature=temperature, top_k=top_k,
            tenants=tenants))
        assert None not in tags, "admit() beyond capacity()"
        return tags

    def cancel_tag(self, tag: int) -> bool:
        """Abort one in-flight request (SSE client vanished): its batch row
        frees IMMEDIATELY — the slot becomes admissible to newcomers at the
        next chunk boundary, the `lm.kv_rows_active` gauge stops counting
        it, and a session whose every row was cancelled reads done() (so
        `lm.kv_rows_allocated` returns to baseline too). The row's decoded
        tokens are discarded, not published. Returns False when the tag is
        not live (already finished — cancellation raced completion)."""
        for i, row in enumerate(self.rows):
            if row is not None and row.tag == tag:
                self.rows[i] = None
                usage.note(row.tenant, tokens_out=len(row.tokens))
                engine_timeline.note_cancel()
                with self.lm._lock:
                    self.lm.stats["cancelled"] = (
                        self.lm.stats.get("cancelled", 0) + 1)
                    # the row's share of device time is still real work done
                    self.lm.stats["tokens_generated"] += len(row.tokens)
                    # flush accumulated decode seconds like _finish does: a
                    # fully-cancelled session never reaches _finish, and
                    # tokens credited without their time would inflate the
                    # derived tok/s gauge
                    self.lm.stats["decode_s"] += self.decode_s
                    self.decode_s = 0.0
                return True
        return False

    # --------------------------------------------------------------- decode

    def step(self) -> list:
        """Decode one chunk; returns [(tag, text), ...] for every request
        that finished in it (eos, its own budget, or the session cap)."""
        import jax

        if self.done():
            return self._drain_all()
        chunk = min(self.chunk, self.remaining_steps())
        with self.lm._lock:
            t0 = time.perf_counter()
            self._sub, use = jax.random.split(self._sub)
            keys = jax.random.split(use, chunk)
            (self._cache, self._logits, self._pos, self._done, toks,
             counted) = gpt_mod.decode_chunk(
                self.lm.params, self._cache, self._logits, self._pos,
                self._done, self._kv_valid, keys, self.lm.model_cfg,
                temperature=self._temps, top_k=self._ks, eos_id=self._eos)
            toks = np.asarray(toks)
            counted = np.asarray(counted)
            step_s = time.perf_counter() - t0
            self.decode_s += step_s
        self.steps_done += chunk
        # decode-plane flight recorder (obs/engine_timeline.py), recorded
        # at this EXISTING chunk-boundary host sync — everything below is
        # host bookkeeping on already-materialized values. Occupancy /
        # per-tenant KV-row-seconds are measured over the rows that were
        # live DURING the chunk (before this chunk's finishes free them).
        live_rows = [r for r in self.rows if r is not None]
        kv_live, kv_alloc = self.lm.kv_row_counts()
        engine_timeline.note_decode_step(
            wall_ms=step_s * 1000.0, rows_live=len(live_rows),
            rows_capacity=self.bb, kv_rows_live=kv_live,
            kv_rows_allocated=kv_alloc, steps=chunk)
        if chunk:
            metrics.observe("lm.tpot_ms", step_s * 1000.0 / chunk,
                            labels={"service": "lm"})
        by_tenant: dict = {}
        for row in live_rows:
            by_tenant[row.tenant] = by_tenant.get(row.tenant, 0) + 1
        for tenant, n_rows in by_tenant.items():
            usage.note(tenant, kv_row_seconds=step_s * n_rows)
        now = time.perf_counter()
        finished = []
        for i, row in enumerate(self.rows):
            if row is None:
                continue
            hit_eos = False
            had_tokens = bool(row.tokens)
            for t, c in zip(toks[i], counted[i]):
                if not c:  # EOS (or a post-EOS slot)
                    hit_eos = True
                    break
                row.tokens.append(int(t))
                if len(row.tokens) >= row.want:
                    break
            if not had_tokens and row.tokens and row.first_tok is None:
                # engine-side TTFT: row creation (its prefill started) →
                # its first token materialized on host
                row.first_tok = now
                metrics.observe("lm.ttft_ms",
                                (now - row.created) * 1000.0,
                                labels={"service": "lm"})
            if hit_eos or len(row.tokens) >= row.want:
                finished.append(self._finish(i))
        if self.remaining_steps() <= 0:
            finished += self._drain_all()
        return finished

    def _finish(self, i: int):
        row = self.rows[i]
        self.rows[i] = None
        usage.note(row.tenant, tokens_out=len(row.tokens))
        engine_timeline.note_finish(
            tokens=len(row.tokens),
            ttft_ms=((row.first_tok - row.created) * 1000.0
                     if row.first_tok is not None else None))
        with self.lm._lock:
            self.lm.stats["generate_calls"] += 1
            self.lm.stats["tokens_generated"] += len(row.tokens)
            self.lm.stats["decode_s"] += self.decode_s
            self.decode_s = 0.0
        return (row.tag, self.lm.tokenizer.decode(row.tokens))

    def _drain_all(self) -> list:
        return [self._finish(i) for i, r in enumerate(self.rows)
                if r is not None]
